"""Wrapper optimizers: LookAhead and ModelAverage.

Reference parity: `python/paddle/incubate/optimizer/lookahead.py:1`
(slow/fast weights: every k steps slow += alpha*(fast-slow), fast := slow)
and `python/paddle/incubate/optimizer/modelaverage.py` (running average of
params swapped in for eval via apply()/restore()).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class LookAhead:
    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._slow = None
        self._steps = 0

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def step(self):
        params = self.inner_optimizer._parameter_list
        if self._slow is None:
            # materialized COPIES, not aliases: the inner fused step DONATES
            # the live param buffers, so an alias here would be deleted
            self._slow = [jnp.array(p._value, copy=True) for p in params]
        self.inner_optimizer.step()
        self._steps += 1
        if self._steps % self.k == 0:
            a = self.alpha
            for i, p in enumerate(params):
                self._slow[i] = self._slow[i] + a * (p._value - self._slow[i])
                # fast := slow by VALUE: an alias would hand _slow[i]'s
                # buffer to the next step's donation
                p._value = jnp.array(self._slow[i], copy=True)

    def clear_grad(self, *a, **kw):
        self.inner_optimizer.clear_grad(*a, **kw)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage:
    """Maintains a running average of parameters; `apply()` swaps the
    averages in (eval), `restore()` swaps training weights back.

    Window policy (reference modelaverage.py semantics): the live window
    rolls over into an 'old' accumulator once it reaches
    max(min_average_window, min(max_average_window,
    num_updates * average_window_rate)); the average spans old + live, so
    the effective window tracks ~average_window_rate of training."""

    def __init__(self, parameters, average_window_rate: float = 0.15,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000):
        self._params = list(parameters)
        self._sum = [np.zeros(p.shape, np.float32) for p in self._params]
        self._old_sum = None
        self._cnt = 0
        self._old_cnt = 0
        self._num_updates = 0
        self._backup = None
        self.average_window_rate = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)

    def _window(self) -> int:
        return max(self.min_average_window,
                   min(self.max_average_window,
                       int(self._num_updates * self.average_window_rate) or 1))

    def step(self):
        """Accumulate current weights (call after optimizer.step)."""
        self._num_updates += 1
        for s, p in zip(self._sum, self._params):
            s += np.asarray(p._value)
        self._cnt += 1
        if self._cnt >= self._window():
            # roll the live window into the old accumulator
            self._old_sum = [s.copy() for s in self._sum]
            self._old_cnt = self._cnt
            self._sum = [np.zeros_like(s) for s in self._sum]
            self._cnt = 0

    def apply(self):
        total = self._cnt + self._old_cnt
        if total == 0 or self._backup is not None:
            return  # nothing accumulated / already applied
        self._backup = [p._value for p in self._params]
        for i, p in enumerate(self._params):
            acc = self._sum[i] + (self._old_sum[i] if self._old_sum else 0.0)
            p._value = jnp.asarray(acc / total)

    def restore(self):
        if self._backup is None:
            return
        for p, v in zip(self._params, self._backup):
            p._value = v
        self._backup = None
