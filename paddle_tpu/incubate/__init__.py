"""paddle.incubate parity surface.

Reference parity: `python/paddle/incubate/` — ASP structured sparsity
(`fluid/contrib/sparsity/asp/asp.py`), LookAhead/ModelAverage wrapper
optimizers (`incubate/optimizer/`).
"""
from . import asp  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
