"""paddle.incubate parity surface.

Reference parity: `python/paddle/incubate/` — ASP structured sparsity
(`fluid/contrib/sparsity/asp/asp.py`), LookAhead/ModelAverage wrapper
optimizers (`incubate/optimizer/`).
"""
from . import asp  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401


# ---- segment ops (incubate/tensor/math.py over segment_pool ops) ----
# TPU-first: jax.ops.segment_* are XLA scatter-reductions — exactly the
# primitive the reference's CUDA segment kernels hand-roll.

def _segment(op_name):
    import jax
    import jax.numpy as jnp
    from ..ops._dispatch import ensure_tensor, run_op

    def fn(data, segment_ids, name=None):
        data = ensure_tensor(data)
        ids = ensure_tensor(segment_ids)._value.astype("int32")
        import numpy as np
        n_seg = int(np.asarray(ids).max()) + 1 if ids.size else 0

        def f(d):
            if op_name == "sum":
                return jax.ops.segment_sum(d, ids, n_seg)
            if op_name == "mean":
                s = jax.ops.segment_sum(d, ids, n_seg)
                c = jax.ops.segment_sum(jnp.ones_like(d), ids, n_seg)
                return s / jnp.maximum(c, 1.0)
            if op_name == "max":
                return jax.ops.segment_max(d, ids, n_seg)
            return jax.ops.segment_min(d, ids, n_seg)

        return run_op(f, [data], f"segment_{op_name}")

    fn.__name__ = f"segment_{op_name}"
    return fn


segment_sum = _segment("sum")
segment_mean = _segment("mean")
segment_max = _segment("max")
segment_min = _segment("min")


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Message passing gather-scatter (`incubate/operators/graph_send_recv`):
    out[d] = reduce over edges (s -> d) of x[s]."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..ops._dispatch import ensure_tensor, run_op
    x = ensure_tensor(x)
    src = ensure_tensor(src_index)._value.astype("int32")
    dst = ensure_tensor(dst_index)._value.astype("int32")
    n_out = int(out_size) if out_size is not None else int(x.shape[0])
    pool = pool_type.lower()

    def f(a):
        msgs = jnp.take(a, src, axis=0)
        if pool == "sum":
            return jax.ops.segment_sum(msgs, dst, n_out)
        if pool == "mean":
            s = jax.ops.segment_sum(msgs, dst, n_out)
            c = jax.ops.segment_sum(jnp.ones((msgs.shape[0],), a.dtype),
                                    dst, n_out)
            return s / jnp.maximum(c, 1.0)[(...,) + (None,) * (a.ndim - 1)]
        if pool == "max":
            return jax.ops.segment_max(msgs, dst, n_out)
        if pool == "min":
            return jax.ops.segment_min(msgs, dst, n_out)
        raise ValueError(f"graph_send_recv: bad pool_type {pool_type!r}")

    return run_op(f, [x], "graph_send_recv")


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       return_eids=False, name=None):
    """K-hop neighbor sampling over a CSC graph
    (`incubate/operators/graph_khop_sampler`): host-side like the
    reference's CPU sampler; returns (edge_src, edge_dst, sample_index,
    reindex_nodes) with nodes reindexed to the sampled subgraph."""
    import numpy as np
    from ..core.tensor import Tensor
    row_v = np.asarray(row._value if hasattr(row, "_value") else row)
    col_v = np.asarray(colptr._value if hasattr(colptr, "_value") else colptr)
    seeds = np.asarray(input_nodes._value if hasattr(input_nodes, "_value")
                       else input_nodes).reshape(-1)
    rng = np.random.RandomState(0)
    frontier = seeds
    all_src, all_dst = [], []
    seen = list(seeds)
    pos = {int(n): i for i, n in enumerate(seeds)}
    for k in sample_sizes:
        nxt = []
        for d in frontier:
            lo, hi = int(col_v[int(d)]), int(col_v[int(d) + 1])
            nbrs = row_v[lo:hi]
            if len(nbrs) > k:
                nbrs = rng.choice(nbrs, size=k, replace=False)
            for s in nbrs:
                s = int(s)
                if s not in pos:
                    pos[s] = len(seen)
                    seen.append(s)
                    nxt.append(s)
                all_src.append(pos[s])
                all_dst.append(pos[int(d)])
        frontier = np.asarray(nxt, np.int64)
    return (Tensor(np.asarray(all_src, np.int64)),
            Tensor(np.asarray(all_dst, np.int64)),
            Tensor(np.asarray(seeds, np.int64)),
            Tensor(np.asarray(seen, np.int64)))


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) fused (`incubate/operators/softmax_mask_fuse`) —
    XLA fuses the add into the softmax; this is the API surface."""
    import jax
    from ..ops._dispatch import ensure_tensor, run_op
    return run_op(lambda a, m: jax.nn.softmax(a + m, axis=-1),
                  [ensure_tensor(x), ensure_tensor(mask)],
                  "softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """softmax with the upper triangle masked out (causal scores),
    `incubate/operators/softmax_mask_fuse_upper_triangle`."""
    import jax
    import jax.numpy as jnp
    from ..ops._dispatch import ensure_tensor, run_op

    def f(a):
        n = a.shape[-1]
        m = a.shape[-2]
        mask = jnp.tril(jnp.ones((m, n), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e4), axis=-1)

    return run_op(f, [ensure_tensor(x)], "softmax_mask_fuse_upper_triangle")
