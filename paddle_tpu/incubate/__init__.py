"""paddle.incubate parity surface (experimental APIs live elsewhere in this
build; kept for import compatibility)."""
