"""Black-box flight recorder — forensic ring buffers dumped on failure.

Every MULTICHIP_r0*.json run died rc=124 with ZERO forensic output: no
phase, no last step, no collective sequence. The flight recorder is the
fix — an always-on (flag-gated, overhead-guarded) black box holding

  - the last N step-timeline records (shared ring with `obs/timeline.py`),
  - the last M per-step monitor-counter deltas,
  - the recent collective sequence (name + bytes, from
    `parallel/collective._record`),
  - recent guard/fault events (rollbacks, bad steps, injected faults),

plus the in-flight phase and the still-open step record at dump time.
`dump(path, reason)` writes ONE JSON artifact; automatic dumps fire from
the guard plane (`StepStalledError`, `RankDesyncError`, `DivergedError`,
`PreemptedError`/SIGTERM), serving overload, and the multichip harness'
per-phase deadline — each error type must be REGISTERED
(`register_dump_trigger`), and a tier-1 test walks `GuardError.__subclasses__`
so a future error class without a trigger fails CI.

Automatic dumps are rate-limited per reason (`FLAGS_obs_dump_min_interval_s`)
so an overload storm cannot flood the disk; explicit `dump(path=...)` calls
never are.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["FlightRecorder", "DUMP_SCHEMA", "dump_to_chrome_events"]

# /2 added the "memory" section: the mem-census ring + per-phase HBM peaks
# (obs/memory.py). /3 adds "traces" (the tail-sampled request-trace rings,
# obs/trace.py) and "slo" (error-budget burn, obs/slo.py). /4 adds the
# OPTIONAL correlated-incident identity: "incident_id" (shared by every
# fleet member's dump of one incident, obs/telemetry.py fan-out) and
# "source" (the dumping process's telemetry source name). /5 adds "sync"
# (the runtime deadlock sanitizer's view, utils/syncwatch.py: live
# registered threads with held locks, the observed lock-order graph, and
# any recorded order violations — {"enabled": False} when FLAGS_sync_watch
# is off). `monitor show` renders every version — an older dump is simply
# one without the section.
DUMP_SCHEMA = "paddle_tpu.flight_recorder/5"

_COLLECTIVE_RING = 256
_EVENT_RING = 128


def _rank() -> int:
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return 0


class FlightRecorder:
    """One per process. Reads the step ring off the shared StepTimeline;
    owns the monitor-delta / collective / event rings."""

    def __init__(self, timeline, snapshot_ring: int = 16):
        self.timeline = timeline
        self._lock = threading.Lock()
        self._deltas: deque = deque(maxlen=max(1, int(snapshot_ring)))
        self._collectives: deque = deque(maxlen=_COLLECTIVE_RING)
        self._events: deque = deque(maxlen=_EVENT_RING)
        self._last_counters: Optional[Dict[str, Any]] = None
        self._last_dump: Dict[str, float] = {}   # reason -> monotonic ts
        self.dumps: List[str] = []               # paths written this process

    # ---- feeders ----
    def on_step_end(self, record: Dict[str, Any]) -> None:
        """Timeline close hook: capture the monitor-counter delta this step
        produced (retraces, collective bytes, guard recoveries...)."""
        from .. import monitor as _monitor
        counters = _monitor.snapshot()["counters"]
        with self._lock:
            prev = self._last_counters or {}
            delta = {k: v - prev.get(k, 0) for k, v in counters.items()
                     if v != prev.get(k, 0)}
            self._last_counters = counters
            self._deltas.append({"step": record.get("step"),
                                 "ts": record.get("t1"), "delta": delta})

    def record_collective(self, name: str, nbytes: int) -> None:
        with self._lock:
            self._collectives.append([time.time(), name, int(nbytes)])

    def record_event(self, kind: str, **payload) -> None:
        ev = {"ts": time.time(), "event": kind}
        ev.update(payload)
        with self._lock:
            self._events.append(ev)

    # ---- dump ----
    def _rate_limited(self, reason: str) -> bool:
        from ..core import flags as _flags
        min_s = float(_flags.flag("obs_dump_min_interval_s"))
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason)
            if last is not None and now - last < min_s:
                return True
            self._last_dump[reason] = now
            return False

    def dump(self, path: Optional[str] = None, reason: str = "manual",
             extra: Optional[Dict[str, Any]] = None,
             incident_id: Optional[str] = None,
             source: Optional[str] = None) -> Optional[str]:
        """Write the black box as one JSON artifact. Returns the path, or
        None when an automatic (path-less) dump was rate-limited.
        `incident_id`/`source` stamp a correlated fleet incident (/4):
        the telemetry fan-out passes an explicit per-incident path, so a
        whole-fleet dump is never suppressed by the per-reason limiter."""
        auto = path is None
        if auto and self._rate_limited(reason):
            return None
        if path is None:
            from ..core import flags as _flags
            d = str(_flags.flag("obs_dump_dir")) or "flight_recorder"
            path = os.path.join(
                d, f"flightrec_{int(time.time() * 1000)}_{reason}"
                   f"_p{os.getpid()}.json")
        payload = self.payload(reason=reason, extra=extra,
                               incident_id=incident_id, source=source)
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)
        with self._lock:
            self.dumps.append(path)
        from .. import monitor as _monitor
        if _monitor._ENABLED:
            _monitor.count("obs.dumps")
            _monitor.log_event("obs.dump", reason=reason, path=path)
        from . import telemetry as _telemetry
        if _telemetry._DEFAULT is not None:
            _telemetry.emit("dump", reason=reason, path=path,
                            incident_id=incident_id, source=source)
        return path

    def payload(self, reason: str = "manual",
                extra: Optional[Dict[str, Any]] = None,
                incident_id: Optional[str] = None,
                source: Optional[str] = None) -> Dict[str, Any]:
        from .. import monitor as _monitor
        tl = self.timeline
        with self._lock:
            deltas = list(self._deltas)
            collectives = list(self._collectives)
            events = list(self._events)
        snap = _monitor.snapshot()
        out = {
            "schema": DUMP_SCHEMA,
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "rank": _rank(),
            "inflight_phase": tl.inflight_phase(),
            "open_step": tl.open_record(),
            "steps": tl.records(),
            "monitor_deltas": deltas,
            "collectives": collectives,
            "events": events,
            "monitor": {"counters": snap["counters"],
                        "gauges": snap["gauges"],
                        "events": snap["events"][-32:]},
        }
        if incident_id is not None:
            out["incident_id"] = incident_id
        if source is not None:
            out["source"] = source
        from . import memory as _memory
        out["memory"] = {"census": _memory.census_ring(),
                         "phase_peaks": _memory.phase_peaks()}
        from . import slo as _slo
        from . import trace as _trace
        out["traces"] = _trace.ring_payload()
        out["slo"] = _slo.stats()
        from ..utils import syncwatch as _syncwatch
        out["sync"] = _syncwatch.dump_sync()
        if extra:
            out["extra"] = extra
        return out


def dump_to_chrome_events(dump: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Flight-recorder dump -> chrome trace events (the
    `python -m paddle_tpu.monitor trace` conversion): step/phase spans from
    the records, instant events for guard/fault events and collectives."""
    from .timeline import records_to_chrome_events
    pid = int(dump.get("pid", 0))
    rank = int(dump.get("rank", 0))
    records = list(dump.get("steps", []))
    if dump.get("open_step"):
        records.append(dump["open_step"])
    events = records_to_chrome_events(records, pid=pid, rank=rank)
    for ev in dump.get("events", []):
        events.append({"name": ev.get("event", "event"), "ph": "i",
                       "s": "p", "ts": float(ev.get("ts", 0.0)) * 1e6,
                       "pid": pid, "tid": rank * 10 + 3,
                       "args": {k: v for k, v in ev.items()
                                if k not in ("ts", "event")}})
    for ts, name, nbytes in dump.get("collectives", []):
        events.append({"name": name, "ph": "i", "s": "t",
                       "ts": float(ts) * 1e6, "pid": pid,
                       "tid": rank * 10 + 4, "args": {"bytes": nbytes}})
    if dump.get("inflight_phase"):
        events.append({"name": f"INFLIGHT: {dump['inflight_phase']}",
                       "ph": "i", "s": "g",
                       "ts": float(dump.get("ts", 0.0)) * 1e6,
                       "pid": pid, "tid": rank * 10})
    traces = dump.get("traces") or {}
    if traces:
        from .trace import trace_chrome_events
        events.extend(trace_chrome_events(
            list(traces.get("kept", [])) + list(traces.get("ring", [])),
            pid=pid))
    return events
