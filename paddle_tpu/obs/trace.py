"""Request-scoped distributed tracing — per-request spans across the wire.

The obs stack attributes *training steps* (obs/timeline.py) and *HBM bytes*
(obs/memory.py); this module attributes *serving requests*: one trace per
client request, spans covering client-send → queue_wait → batch-coalesce →
predictor dispatch → reply, across processes. It is the reference's
profiler/STAT plane (PAPER.md §1 row 1) extended to where Paddle never
went: request-scoped, cross-process, SLO-bearing.

Model (OpenTelemetry-shaped, dependency-free):

  - `TraceContext(trace_id, span_id, flags)` — what crosses a process
    boundary. 26 bytes on the wire (`pack_ctx`/`unpack_ctx`): u8 version,
    16-byte trace id, 8-byte span id, u8 flags. The serving protocol
    carries it in an optional `'PDTC'` prefix frame
    (`inference/server.py`); the fleet message bus appends it to the
    message tuple (`distributed/fleet_executor.py`) — ABSENCE of either
    means "no trace", so old clients/servers interoperate bit-identically.
  - `Span` — one timed operation: (trace_id, span_id, parent_id, name,
    status, attrs, links). `links` lets a batch span reference the member
    request spans it coalesced (many traces meet in one batch; the batch
    belongs to no single one).
  - per-thread ACTIVE SPAN STACK: `span(name)` parents onto the innermost
    open span (or the explicit `ctx=`), so call sites never thread ids by
    hand. The autouse `_no_trace_leak` test fixture asserts the stack is
    empty after every test — an error path that forgets to close a span
    is a bug, not a shrug.
  - TAIL-SAMPLED RING: finished traces land in two bounded rings — one
    for healthy traces (any of which sampling may drop), one PROTECTED
    ring for traces that ended over-deadline, rejected, errored, or
    slower than the SLO objective (always kept: the interesting traces
    are exactly the ones head sampling would have thrown away). Both
    rings join the flight-recorder dump (schema v3) and export to
    chrome-trace events.

Finished spans also feed the existing `monitor.span()` dispatcher
(`monitor.record_span`): `span.trace.<name>.dur` histograms (the new
sketch gives them real p99s) and any active Profiler's host-event stream,
so `Profiler.export` carries the request plane next to op dispatch and
step phases.

Hot-path contract (same as monitor/faults/obs): instrumented sites check
ONE module attribute (`_trace._ENABLED`) and allocate nothing on the
disabled path — `span()` returns a shared no-op context; the tier-1
overhead guard enforces it.
"""
from __future__ import annotations

import os
import struct
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..core import flags as _flags

__all__ = [
    "TraceContext", "Span", "span", "server_span", "current", "context",
    "pack_ctx", "unpack_ctx", "CTX_WIRE_LEN", "new_trace_id", "new_span_id",
    "traces", "bad_traces", "ring_payload", "trace_chrome_events",
    "active_depth", "reset", "enabled",
    "STATUS_OK", "STATUS_ERROR", "STATUS_DEADLINE", "STATUS_REJECTED",
    "STATUS_SLO_VIOLATION",
]

# span terminal statuses. "ok" traces ride the sampled ring; every other
# status lands in the protected ring (tail sampling keeps failures).
STATUS_OK = "ok"
STATUS_ERROR = "error"
STATUS_DEADLINE = "deadline"
STATUS_REJECTED = "rejected"
STATUS_SLO_VIOLATION = "slo_violation"

_BAD_STATUSES = (STATUS_ERROR, STATUS_DEADLINE, STATUS_REJECTED,
                 STATUS_SLO_VIOLATION)

# ---- gate -------------------------------------------------------------------

_ENABLED: bool = bool(_flags.flag("trace"))


def _on_flag(value) -> None:
    global _ENABLED
    _ENABLED = bool(value)


_flags.watch_flag("trace", _on_flag)


def enabled() -> bool:
    return _ENABLED


# ---- ids + wire context -----------------------------------------------------

_CTX_VERSION = 1
CTX_WIRE_LEN = 26  # u8 version + 16B trace id + 8B span id + u8 flags


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


class TraceContext:
    """What crosses a process boundary: enough to parent a remote span."""

    __slots__ = ("trace_id", "span_id", "flags")

    def __init__(self, trace_id: str, span_id: str, flags: int = 0):
        self.trace_id = trace_id
        self.span_id = span_id
        self.flags = flags

    def __repr__(self):
        return (f"TraceContext({self.trace_id[:8]}…, "
                f"{self.span_id}, flags={self.flags})")

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id
                and self.flags == other.flags)


def pack_ctx(ctx: TraceContext) -> bytes:
    """26-byte wire form of a trace context (the 'PDTC' frame body and
    the bus message trailer)."""
    return struct.pack("<B16s8sB", _CTX_VERSION,
                       bytes.fromhex(ctx.trace_id),
                       bytes.fromhex(ctx.span_id), ctx.flags & 0xFF)


def unpack_ctx(raw: bytes) -> TraceContext:
    version, tid, sid, fl = struct.unpack("<B16s8sB", raw)
    if version != _CTX_VERSION:
        raise ValueError(f"unknown trace context version {version}")
    return TraceContext(tid.hex(), sid.hex(), fl)


# ---- spans ------------------------------------------------------------------

_TLS = threading.local()


def _stack() -> List["Span"]:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def active_depth() -> int:
    """Open spans on the CALLING thread (the `_no_trace_leak` fixture
    asserts 0 after every test) plus every span opened but not yet closed
    process-wide (cross-thread request spans held by the engine)."""
    return len(_stack()) + len(_BUFFER.open_spans())


class Span:
    """One timed operation inside a trace. Close with `end(status=...)` or
    use as a context manager (an exception sets status=error)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0", "t1",
                 "status", "attrs", "links", "_on_stack")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 attrs: Optional[Dict[str, Any]] = None,
                 links: Optional[List[Tuple[str, str]]] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.t0 = time.time()
        self.t1: Optional[float] = None
        self.status = STATUS_OK
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.links: List[Tuple[str, str]] = list(links) if links else []
        self._on_stack = False
        _BUFFER.opened(self)   # leak watch: closed again in end()

    # -- wire handoff --
    def ctx(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    def link(self, other: "Span") -> None:
        """Reference another span without parenting it (batch spans link
        the member request spans they coalesced)."""
        self.links.append((other.trace_id, other.span_id))

    def link_ctx(self, ctx: TraceContext) -> None:
        self.links.append((ctx.trace_id, ctx.span_id))

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    # -- lifecycle --
    def end(self, status: Optional[str] = None, **attrs) -> None:
        if self.t1 is not None:   # idempotent: error paths may race reply
            return
        self.t1 = time.time()
        if status is not None:
            self.status = status
        if attrs:
            self.attrs.update(attrs)
        _BUFFER.finish(self)
        from .. import monitor as _monitor
        if _monitor._ENABLED:
            _monitor.record_span(f"trace.{self.name}", self.t0, self.t1,
                                 kind="trace")

    def __enter__(self) -> "Span":
        _stack().append(self)
        self._on_stack = True
        return self

    def __exit__(self, exc_type, exc, tb):
        st = _stack()
        if self._on_stack and self in st:
            st.remove(self)
        self._on_stack = False
        if exc is not None and self.status == STATUS_OK:
            self.end(status=STATUS_ERROR,
                     error=f"{type(exc).__name__}: {str(exc)[:200]}")
        else:
            self.end()
        return False

    def to_dict(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "t0": self.t0, "t1": self.t1, "status": self.status,
                "attrs": self.attrs, "links": self.links}


class _NullSpan:
    """Shared no-op span: the disabled path allocates nothing."""

    __slots__ = ()
    trace_id = None
    span_id = None
    status = STATUS_OK

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def end(self, status=None, **attrs):
        pass

    def set(self, **attrs):
        return self

    def link(self, other):
        pass

    def link_ctx(self, ctx):
        pass

    def ctx(self):
        return None


NULL_SPAN = _NullSpan()


def span(name: str, ctx: Optional[TraceContext] = None,
         attrs: Optional[Dict[str, Any]] = None,
         links: Optional[List[Tuple[str, str]]] = None):
    """Open a span: child of `ctx` when given, else of the calling
    thread's innermost open span, else the root of a NEW trace. Disabled
    -> shared no-op span (one module-attribute check)."""
    if not _ENABLED:
        return NULL_SPAN
    if ctx is not None:
        return Span(name, ctx.trace_id, ctx.span_id, attrs, links)
    st = _stack()
    if st:
        parent = st[-1]
        return Span(name, parent.trace_id, parent.span_id, attrs, links)
    return Span(name, new_trace_id(), None, attrs, links)


def server_span(name: str, ctx: Optional[TraceContext],
                attrs: Optional[Dict[str, Any]] = None):
    """Span for the receiving side of a wire hop: ONLY opens when the
    caller actually sent a context (absence means "no trace" — an
    untraced request must not mint server-side garbage traces)."""
    if not _ENABLED or ctx is None:
        return NULL_SPAN
    return Span(name, ctx.trace_id, ctx.span_id, attrs)


def current() -> Optional[Span]:
    st = _stack()
    return st[-1] if st else None


def context() -> Optional[TraceContext]:
    """Wire context of the calling thread's innermost open span (what a
    client injects into the 'PDTC' frame / bus message), or None."""
    if not _ENABLED:
        return None
    sp = current()
    return sp.ctx() if sp is not None else None


# ---- tail-sampled trace ring ------------------------------------------------

class TraceBuffer:
    """Finished spans grouped per trace, in two bounded rings: `ok`
    (healthy traces — evictable) and `bad` (over-deadline / rejected /
    errored / SLO-violating — protected: an overload storm of healthy
    traffic cannot evict the forensic traces). A trace moves rings the
    moment any of its spans ends non-ok."""

    def __init__(self, capacity: int = 64):
        self._lock = threading.Lock()
        cap = max(1, int(capacity))
        self._ok: "deque[Dict[str, Any]]" = deque(maxlen=cap)
        self._bad: "deque[Dict[str, Any]]" = deque(maxlen=cap)
        self._open: Dict[int, Span] = {}   # id(span) -> span (leak watch)

    # -- open-span accounting (the no-leak fixture reads this) --
    def opened(self, sp: Span) -> None:
        with self._lock:
            self._open[id(sp)] = sp

    def open_spans(self) -> List[Span]:
        with self._lock:
            return list(self._open.values())

    def finish(self, sp: Span) -> None:
        with self._lock:
            self._open.pop(id(sp), None)
            rec = self._find(sp.trace_id)
            if rec is None:
                rec = {"trace_id": sp.trace_id, "status": STATUS_OK,
                       "t0": sp.t0, "t1": sp.t1, "spans": []}
                self._ok.append(rec)
            rec["spans"].append(sp.to_dict())
            rec["t0"] = min(rec["t0"], sp.t0)
            rec["t1"] = max(rec["t1"] or sp.t1, sp.t1)
            if sp.status != STATUS_OK and rec["status"] == STATUS_OK:
                rec["status"] = sp.status
                # promote to the protected ring
                try:
                    self._ok.remove(rec)
                except ValueError:
                    pass
                self._bad.append(rec)
            from .. import monitor as _monitor
            if _monitor._ENABLED:
                _monitor.count("trace.spans")
                if sp.status != STATUS_OK:
                    _monitor.count(f"trace.spans.{sp.status}")

    def _find(self, trace_id: str) -> Optional[Dict[str, Any]]:
        for ring in (self._bad, self._ok):
            for rec in reversed(ring):
                if rec["trace_id"] == trace_id:
                    return rec
        return None

    def trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._find(trace_id)
            return dict(rec, spans=list(rec["spans"])) if rec else None

    def traces(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r, spans=list(r["spans"]))
                    for r in list(self._ok) + list(self._bad)]

    def bad_traces(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r, spans=list(r["spans"])) for r in self._bad]

    def payload(self) -> Dict[str, Any]:
        """The flight-recorder dump section (schema v3)."""
        with self._lock:
            return {"ring": [dict(r, spans=list(r["spans"]))
                             for r in list(self._ok)],
                    "kept": [dict(r, spans=list(r["spans"]))
                             for r in list(self._bad)]}

    def reset(self) -> None:
        with self._lock:
            self._ok.clear()
            self._bad.clear()
            self._open.clear()


def _make_buffer() -> TraceBuffer:
    return TraceBuffer(capacity=int(_flags.flag("trace_ring")))


_BUFFER = _make_buffer()


def _on_ring_flag(_v) -> None:
    global _BUFFER
    _BUFFER = _make_buffer()


_flags.watch_flag("trace_ring", _on_ring_flag)


def buffer() -> TraceBuffer:
    return _BUFFER


def traces() -> List[Dict[str, Any]]:
    return _BUFFER.traces()


def bad_traces() -> List[Dict[str, Any]]:
    return _BUFFER.bad_traces()


def ring_payload() -> Dict[str, Any]:
    return _BUFFER.payload()


def reset() -> None:
    _BUFFER.reset()
    _TLS.stack = []


# ---- export -----------------------------------------------------------------

def trace_chrome_events(trace_docs: List[Dict[str, Any]],
                        pid: int = 0) -> List[Dict[str, Any]]:
    """Trace-ring entries -> chrome `ph:"X"` events. Each trace gets its
    own tid lane so concurrent requests read as parallel tracks; span args
    carry ids + status so a slow request can be chased across processes."""
    events: List[Dict[str, Any]] = []
    for lane, doc in enumerate(trace_docs):
        for sp in doc.get("spans", []):
            t0 = float(sp.get("t0", 0.0))
            t1 = float(sp.get("t1") or t0)
            events.append({
                "name": sp.get("name", "span"), "ph": "X", "cat": "trace",
                "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6,
                "pid": pid, "tid": 100 + lane,
                "args": {"trace_id": doc.get("trace_id"),
                         "span_id": sp.get("span_id"),
                         "parent_id": sp.get("parent_id"),
                         "status": sp.get("status"),
                         **(sp.get("attrs") or {})}})
    return events


def render_traces(trace_docs: List[Dict[str, Any]], limit: int = 8) -> str:
    """Text rendering for `monitor show`/`slo` — worst (slowest non-ok
    first) traces with their span waterfall."""
    def _key(doc):
        dur = (doc.get("t1") or 0.0) - (doc.get("t0") or 0.0)
        return (0 if doc.get("status") != STATUS_OK else 1, -dur)

    lines: List[str] = []
    for doc in sorted(trace_docs, key=_key)[:limit]:
        dur = ((doc.get("t1") or 0.0) - (doc.get("t0") or 0.0)) * 1e3
        lines.append(f"trace {doc.get('trace_id', '?')[:16]}  "
                     f"status={doc.get('status')}  {dur:.2f}ms  "
                     f"{len(doc.get('spans', []))} spans")
        t_base = doc.get("t0") or 0.0
        for sp in sorted(doc.get("spans", []),
                         key=lambda s: s.get("t0", 0.0)):
            t0 = float(sp.get("t0", 0.0))
            t1 = float(sp.get("t1") or t0)
            mark = "" if sp.get("status") == STATUS_OK \
                else f"  !{sp.get('status')}"
            lines.append(f"  +{(t0 - t_base) * 1e3:8.2f}ms "
                         f"{(t1 - t0) * 1e3:8.2f}ms  "
                         f"{sp.get('name')}{mark}")
    return "\n".join(lines)
