"""Step timeline — per-step phase attribution for the training hot loop.

Reference parity: the dedicated profiler/STAT plane (`paddle/fluid/platform/
profiler/` + `monitor.h`, PAPER.md §1 row 1) whose RecordEvent ranges name
*which part* of a step the time went to. `monitor.py` (PR 1) gives flat
counters and ad-hoc spans; this module structures them into one record per
training step:

    {"step": 17, "t0": ..., "t1": ..., "wall": 0.0123,
     "phases":  {"h2d": 0.0004, "device_compute": 0.0115, ...},
     "spans":   [["h2d", t0, t1], ...],          # for chrome export
     "between": {"data_wait": 0.0021, ...}}      # time spent BETWEEN steps

`phases` holds only time spent inside the step window, so
`sum(phases.values()) ≈ wall` is an invariant (tested); work that happens
between steps (DataLoader queue wait, guard snapshots after the step
closes) accumulates in a pending bucket and is folded into the NEXT
record's `between` dict — visible, but never double-counted against wall.

Records live in a bounded ring (`FLAGS_obs_ring_steps`); the flight
recorder (`obs/recorder.py`) shares the same ring. Chrome-trace export
(`ph:"X"`) merges with any `paddle_tpu.profiler.Profiler`'s host events so
one artifact carries op dispatch, monitor spans, and step phases.

Phase vocabulary used by the instrumented call sites:
  data_wait       DataLoader consumer stalled on the worker queue (io/)
  h2d             batch → device-array conversion (jit/, parallel/)
  prefetch_h2d    async feeder-thread device_put (io/prefetch.py) — HIDDEN
                  time booked via add_async_phase: it overlaps steps, so it
                  lands in `between`, never inside a step window
  build           TrainStep._build: module-tree walk + slot init
  trace_compile   first dispatch of a novel batch signature (jax trace +
                  XLA compile + run)
  device_compute  steady-state dispatch, fenced by block_until_ready
                  (lazy eager segment flushes — `ops/lazy.py` under
                  FLAGS_lazy_eager — book here too: a novel segment
                  signature lands in trace_compile, a cached replay in
                  device_compute, so deferred work is attributed at the
                  flush instead of smeared over the deferring ops)
  collective      eager collective API calls (parallel/collective.py)
  optimizer       eager Optimizer.step (jitted paths fuse it into
                  device_compute)
  snapshot        guard rolling in-memory snapshot
  checkpoint      guard durable checkpoint commit
  desync          guard cross-rank fingerprint exchange
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = ["StepTimeline", "PHASES"]

PHASES = ("data_wait", "h2d", "prefetch_h2d", "build", "trace_compile",
          "device_compute", "collective", "optimizer", "snapshot",
          "checkpoint", "desync")

_MAX_SPANS_PER_STEP = 128

# Per-thread count of open `with phase(...)` contexts. The executable
# substrate (core/executable.py) reads this to suppress a nested booking:
# when a dispatch site already sits inside an enclosing phase (a lazy
# flush inside a TrainStep's device_compute, say), opening a second
# phase would book the same wall time twice and break the
# phase-sum≈wall invariant.
_PHASE_TLS = threading.local()


def thread_phase_depth() -> int:
    return getattr(_PHASE_TLS, "depth", 0)


class _NullCtx:
    """Shared no-op context: disabled phase()/step_record() allocate nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_CTX = _NullCtx()


class _Phase:
    __slots__ = ("_tl", "name", "_t0", "_token")

    def __init__(self, tl: "StepTimeline", name: str):
        self._tl = tl
        self.name = name

    def __enter__(self):
        self._t0 = time.time()
        self._token = self._tl._enter_phase(self.name, self._t0)
        return self

    def __exit__(self, *exc):
        self._tl._exit_phase(self._token, self.name, self._t0, time.time())
        return False


class _StepCtx:
    __slots__ = ("_tl",)

    def __init__(self, tl: "StepTimeline"):
        self._tl = tl

    def __enter__(self):
        self._tl._step_enter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tl._step_exit(exc)
        return False


class StepTimeline:
    """Bounded ring of per-step phase records. Thread-safe: phases may be
    reported from the watchdog runner / DataLoader consumer threads while
    the step record is owned by the training thread. Reentrant: nested
    step_record() calls (TrainGuard.step wrapping TrainStep.__call__) share
    one record — the outermost owner opens and closes it."""

    def __init__(self, capacity: int = 64):
        self._lock = threading.RLock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._open: Optional[Dict[str, Any]] = None
        self._depth = 0
        self._step_no = 0
        self._pending: Dict[str, float] = {}
        self._pending_spans: List[List] = []
        self._open_spans: Dict[int, tuple] = {}   # token -> (name, t0)
        self._next_token = 0
        self._marker: Optional[tuple] = None      # (name, ts) from mark()
        # recorder hook: called with the closed record (obs wires this)
        self.on_close: Optional[Callable[[Dict[str, Any]], None]] = None
        # phase-boundary hook: called as (name, t0, t1) at every phase
        # exit (obs wires the memory plane's peak-HBM sampler here when
        # both FLAGS_obs_timeline and FLAGS_mem_census are on)
        self.on_phase: Optional[Callable[[str, float, float], None]] = None

    # ---- step record lifecycle ----
    def step_record(self) -> _StepCtx:
        return _StepCtx(self)

    def _step_enter(self) -> None:
        with self._lock:
            self._depth += 1
            if self._depth > 1:
                return
            self._step_no += 1
            self._open = {
                "step": self._step_no,
                "t0": time.time(),
                "phases": {},
                "spans": [],
                "between": self._pending,
                "between_spans": self._pending_spans[:_MAX_SPANS_PER_STEP],
            }
            self._pending = {}
            self._pending_spans = []

    def _step_exit(self, exc) -> None:
        with self._lock:
            self._depth -= 1
            if self._depth > 0 or self._open is None:
                return
            rec = self._open
            self._open = None
            rec["t1"] = time.time()
            rec["wall"] = rec["t1"] - rec["t0"]
            if exc is not None:
                rec["error"] = f"{type(exc).__name__}: {str(exc)[:200]}"
                rec["inflight"] = self.inflight_phase()
            self._ring.append(rec)
            hook = self.on_close
        if hook is not None:
            hook(rec)

    # ---- phases ----
    def phase(self, name: str) -> _Phase:
        return _Phase(self, name)

    def _enter_phase(self, name: str, t0: float) -> int:
        _PHASE_TLS.depth = getattr(_PHASE_TLS, "depth", 0) + 1
        with self._lock:
            self._next_token += 1
            self._open_spans[self._next_token] = (name, t0)
            return self._next_token

    def _exit_phase(self, token: int, name: str, t0: float, t1: float) -> None:
        _PHASE_TLS.depth = max(0, getattr(_PHASE_TLS, "depth", 1) - 1)
        with self._lock:
            self._open_spans.pop(token, None)
        self.add_phase(name, t1 - t0, t0, t1)
        hook = self.on_phase
        if hook is not None:
            hook(name, t0, t1)

    def add_phase(self, name: str, dur: float,
                  t0: Optional[float] = None,
                  t1: Optional[float] = None) -> None:
        """Fold a measured duration into the open step record, or into the
        pending between-steps bucket when no step is open."""
        with self._lock:
            rec = self._open
            if rec is not None:
                phases, spans = rec["phases"], rec["spans"]
            else:
                phases, spans = self._pending, self._pending_spans
            phases[name] = phases.get(name, 0.0) + float(dur)
            if t0 is not None and len(spans) < _MAX_SPANS_PER_STEP:
                spans.append([name, t0, t1 if t1 is not None else t0 + dur])

    def add_async_phase(self, name: str, dur: float,
                        t0: Optional[float] = None,
                        t1: Optional[float] = None) -> None:
        """Book time that ran CONCURRENTLY with steps on another thread
        (prefetch feeder h2d, background checkpoint IO). It always lands in
        the pending between-steps bucket — never inside the open step
        record — so hidden work stays visible in summaries without breaking
        the in-window phases-sum≈wall invariant or double-counting against
        device_compute."""
        with self._lock:
            phases, spans = self._pending, self._pending_spans
            phases[name] = phases.get(name, 0.0) + float(dur)
            if t0 is not None and len(spans) < _MAX_SPANS_PER_STEP:
                spans.append([name, t0, t1 if t1 is not None else t0 + dur])

    def mark(self, name: str) -> None:
        """Cheap progress marker (no duration): the watchdog reports its
        step phase here so a wedged step's dump can name where it hung
        even when the wedge sits between timeline phase spans."""
        self._marker = (name, time.time())

    def inflight_phase(self) -> Optional[str]:
        """Name of the innermost currently-open phase span, falling back
        to the last mark() — the 'where were we' field of a crash dump."""
        with self._lock:
            if self._open_spans:
                return max(self._open_spans.values(), key=lambda v: v[1])[0]
        if self._marker is not None:
            return self._marker[0]
        return None

    # ---- read side ----
    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def last(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def open_record(self) -> Optional[Dict[str, Any]]:
        """Shallow snapshot of the in-flight (unclosed) step record — what
        the flight recorder captures when a step dies mid-way."""
        with self._lock:
            if self._open is None:
                return None
            rec = dict(self._open)
            rec["phases"] = dict(rec["phases"])
            rec["spans"] = list(rec["spans"])
            return rec

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._open = None
            self._depth = 0
            self._step_no = 0
            self._pending = {}
            self._pending_spans = []
            self._open_spans = {}
            self._marker = None

    # ---- aggregation / reports ----
    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-phase aggregate over the ring: {phase: {count, total, mean}}.
        Between-steps phases (data_wait, post-step guard work) are included
        under their own names — they are real wall time, just not inside
        any step window."""
        agg: Dict[str, Dict[str, float]] = {}
        for rec in self.records():
            for src in ("phases", "between"):
                for name, dur in rec.get(src, {}).items():
                    a = agg.setdefault(name,
                                       {"count": 0, "total": 0.0, "mean": 0.0})
                    a["count"] += 1
                    a["total"] += dur
        for a in agg.values():
            a["mean"] = a["total"] / a["count"] if a["count"] else 0.0
        return agg

    def report(self, time_unit: str = "ms") -> str:
        scale = {"s": 1.0, "ms": 1e3, "us": 1e6}.get(time_unit, 1e3)
        recs = self.records()
        lines = ["-" * 64,
                 f"step timeline ({len(recs)} steps in ring)",
                 "-" * 64,
                 f"{'Phase':<24}{'Steps':>8}{'Total(' + time_unit + ')':>14}"
                 f"{'Mean':>12}"]
        agg = self.summary()
        for name in sorted(agg, key=lambda n: -agg[n]["total"]):
            a = agg[name]
            lines.append(f"{name[:23]:<24}{a['count']:>8}"
                         f"{a['total'] * scale:>14.3f}"
                         f"{a['mean'] * scale:>12.3f}")
        if recs:
            walls = [r["wall"] for r in recs]
            lines.append("-" * 64)
            lines.append(f"{'step wall':<24}{len(walls):>8}"
                         f"{sum(walls) * scale:>14.3f}"
                         f"{sum(walls) / len(walls) * scale:>12.3f}")
        lines.append("-" * 64)
        return "\n".join(lines)

    # ---- chrome export ----
    def chrome_events(self, pid: Optional[int] = None) -> List[Dict[str, Any]]:
        pid = os.getpid() if pid is None else pid
        return records_to_chrome_events(self.records(), pid=pid)

    def export_chrome(self, path: str, profiler=None) -> str:
        """Write a chrome://tracing JSON: step + phase `ph:"X"` events,
        merged with an (optional) Profiler's host events and the monitor
        counter snapshot — one artifact, all three planes."""
        import json
        events = self.chrome_events()
        if profiler is not None:
            for e in profiler.events():
                events.append({"name": e.name, "ph": "X", "cat": e.kind,
                               "ts": e.start * 1e6, "dur": e.dur * 1e6,
                               "pid": os.getpid(), "tid": e.tid})
        from .. import monitor as _monitor
        snap = _monitor.snapshot()
        events.append({"name": "paddle_tpu.monitor", "ph": "M",
                       "pid": os.getpid(), "tid": 0, "args": snap})
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"},
                      f, default=str)
        return path


def records_to_chrome_events(records, pid: int = 0,
                             rank: Optional[int] = None):
    """Step records -> chrome trace `ph:"X"` events. Steps land on tid 0,
    in-step phase spans on tid 1, between-step spans on tid 2 (rank*10
    offsets keep a merged pod timeline readable)."""
    base = (rank or 0) * 10
    events = []
    for rec in records:
        name = f"step {rec.get('step', '?')}"
        if rank is not None:
            name = f"r{rank} {name}"
        if "t0" in rec:
            events.append({"name": name, "ph": "X", "cat": "step",
                           "ts": rec["t0"] * 1e6,
                           "dur": rec.get("wall", 0.0) * 1e6,
                           "pid": pid, "tid": base,
                           "args": {"phases": rec.get("phases", {}),
                                    "error": rec.get("error")}})
        for tid_off, key in ((1, "spans"), (2, "between_spans")):
            for span in rec.get(key, []):
                sname, t0, t1 = span[0], span[1], span[2]
                events.append({"name": sname, "ph": "X", "cat": "phase",
                               "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6,
                               "pid": pid, "tid": base + tid_off})
    return events
