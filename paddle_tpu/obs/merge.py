"""Cross-rank timeline merge — the pod-level straggler hunter.

On a pod slice, "the step is slow" is useless until a rank and a phase are
named: rank 13's data_wait stretching every collective, one host's h2d
crawling, a single straggler dragging the allreduce. Each rank records its
own `StepTimeline`; `gather_timelines` exchanges slimmed records through
the job's rendezvous store (`collective.store_all_gather_object` — the same
cross-process regime the desync detector uses, so no extra infrastructure),
`merge_timelines` aligns them into one pod timeline, and
`straggler_report` names the worst rank per phase with its skew over the
group median. Surfaced as `TrainGuard.timeline_report()` and exercised by
the multichip harness.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

__all__ = ["gather_timelines", "merge_timelines", "straggler_report",
           "slim_records", "skew_over_median"]


def skew_over_median(values: Dict[Any, float]):
    """The straggler rule shared by the pod-timeline merge and the fleet
    `monitor top` table: the member with the largest value, the group
    median, and worst/median skew (inf when the median is 0 but the worst
    is not — one member doing ALL the waiting). Returns
    (worst_key, worst_value, median, skew); (None, 0, 0, 0) when empty."""
    if not values:
        return None, 0.0, 0.0, 0.0
    worst = max(values, key=lambda k: values[k])
    vals = sorted(values.values())
    median = vals[len(vals) // 2] if len(vals) % 2 else \
        0.5 * (vals[len(vals) // 2 - 1] + vals[len(vals) // 2])
    skew = (values[worst] / median) if median > 0 else \
        (0.0 if values[worst] == 0 else float("inf"))
    return worst, values[worst], median, skew


def slim_records(records) -> List[Dict[str, Any]]:
    """Drop the span lists (chrome-export detail) so the store exchange
    ships a few hundred bytes per step, not the full trace."""
    out = []
    for r in records:
        out.append({"step": r.get("step"), "wall": r.get("wall"),
                    "phases": dict(r.get("phases", {})),
                    "between": dict(r.get("between", {})),
                    "error": r.get("error")})
    return out


def gather_timelines(store, rank: int, world_size: int, records,
                     key: str = "obs/timeline",
                     timeout_s: float = 30.0) -> Dict[int, List[Dict]]:
    """All-gather each rank's (slimmed) step records through the rendezvous
    store. Returns {rank: [records]}. Raises TimeoutError when a peer never
    publishes — a hang, not a straggle; callers must not blame that rank."""
    from ..parallel.collective import store_all_gather_object
    payload = slim_records(records)
    gathered = store_all_gather_object(store, key, payload, rank, world_size,
                                       timeout_s=timeout_s)
    return {int(r): v for r, v in gathered.items()}


def merge_timelines(per_rank: Dict[int, List[Dict]]) -> Dict[str, Any]:
    """Fold per-rank records into one pod timeline: per-rank per-phase
    means (in-window and between-step phases both count — a straggler's
    data_wait is exactly the between-step kind), wall means, and a
    straggler verdict per phase: the rank with the largest mean, with its
    skew over the group median."""
    ranks: Dict[int, Dict[str, Any]] = {}
    phase_names = set()
    for rank, records in per_rank.items():
        totals: Dict[str, float] = {}
        counts: Dict[str, int] = {}
        walls: List[float] = []
        for rec in records:
            if rec.get("wall") is not None:
                walls.append(float(rec["wall"]))
            for src in ("phases", "between"):
                for name, dur in (rec.get(src) or {}).items():
                    totals[name] = totals.get(name, 0.0) + float(dur)
                    counts[name] = counts.get(name, 0) + 1
        phase_names.update(totals)
        ranks[rank] = {
            "steps": len(records),
            "wall_mean": sum(walls) / len(walls) if walls else 0.0,
            "phases": {n: {"total": totals[n], "count": counts[n],
                           "mean": totals[n] / counts[n]} for n in totals},
        }
    stragglers: Dict[str, Dict[str, Any]] = {}
    for name in phase_names:
        means = {r: ranks[r]["phases"].get(name, {}).get("mean", 0.0)
                 for r in ranks}
        worst, worst_mean, median, skew = skew_over_median(means)
        stragglers[name] = {
            "rank": worst,
            "mean": worst_mean,
            "group_median": median,
            "skew": skew,
        }
    wall_means = {r: ranks[r]["wall_mean"] for r in ranks}
    slowest = max(wall_means, key=lambda r: wall_means[r]) if wall_means \
        else None
    return {"world_size": len(ranks), "ranks": ranks,
            "stragglers": stragglers, "slowest_rank": slowest}


def straggler_report(merged: Dict[str, Any],
                     time_unit: str = "ms") -> str:
    """Human-readable pod timeline: one line per phase naming the
    straggler rank, its mean, the group median, and the skew factor."""
    scale = {"s": 1.0, "ms": 1e3, "us": 1e6}.get(time_unit, 1e3)
    lines = ["-" * 72,
             f"pod timeline — {merged['world_size']} ranks"
             + (f", slowest rank {merged['slowest_rank']}"
                if merged.get("slowest_rank") is not None else ""),
             "-" * 72,
             f"{'Phase':<20}{'Straggler':>10}{'Mean(' + time_unit + ')':>14}"
             f"{'Median':>12}{'Skew':>8}"]
    strag = merged.get("stragglers", {})
    for name in sorted(strag, key=lambda n: -strag[n]["mean"]):
        s = strag[name]
        skew = f"{s['skew']:.2f}x" if s["skew"] != float("inf") else "inf"
        lines.append(f"{name[:19]:<20}{'rank ' + str(s['rank']):>10}"
                     f"{s['mean'] * scale:>14.3f}"
                     f"{s['group_median'] * scale:>12.3f}{skew:>8}")
    lines.append("-" * 72)
    return "\n".join(lines)
