"""HBM memory attribution plane — the *space* analog of the step timeline.

Reference parity: the allocator stats/introspection role of
`paddle/fluid/memory/` (AllocatorFacade + stats.h StatRegistry, PAPER.md §1
row 2). `paddle.device.memory_stats` answers "how many bytes are live";
nothing answered "whose bytes are they" — when a run OOMs or HBM creeps up
across steps, no plane said whether params, optimizer slots, activations,
prefetch staging, the serving bucket pool, or the lazy segment cache owns
the growth. This module does, three ways:

  1. **Tagged live-buffer census** — `tag(name, values, origin=...)`
     registers device buffers in a weakref side-table at their creation
     seams (`jit/train_step.py`, `parallel/spmd.py`, `optimizer/`,
     `io/prefetch.py`, `serving/engine.py`, `ops/lazy.py`); `census()`
     walks `jax.live_arrays()` and buckets bytes per tag per device
     (untagged = "other"), publishing `mem.<tag>.bytes` gauges and feeding
     a bounded ring (`FLAGS_mem_census_ring`). Tags survive donation
     because the *call sites* re-tag the replacement buffers right after
     committing them — the donated-away buffer leaves its tag with its
     corpse (the weakref callback reaps it), the replacement inherits it.
  2. **Per-executable breakdown** — `executable_memory(compiled)`
     normalizes `compiled.memory_analysis()` (argument/output/temp/
     generated-code/alias bytes) for every cached executable; surfaced as
     `TrainStep.memory_report()`, `SPMDTrainStep.memory_report()`,
     `Optimizer.memory_report()`, `ops.lazy.segment_memory()`. Peak HBM is
     sampled at timeline phase boundaries (`StepTimeline.on_phase`) into
     its own ring, so a dump can say *which phase* the high-water mark
     lives in.
  3. **OOM forensics + leak watch** — `maybe_dump_oom(exc, ...)` turns an
     XLA `RESOURCE_EXHAUSTED` (or a fault injected at the `mem.alloc`
     site) into ONE rate-limited flight-recorder dump embedding the census
     ring, the top-K buffers by size with tag + origin, and the owning
     executable's temp bytes; the leak watch flags any tag whose census
     bytes grow strictly for `FLAGS_mem_leak_window` consecutive censuses
     (`mem.leak_suspects` counter + one warning per tag).

Hot-path contract (monitor/faults/obs regime): every tag seam checks ONE
module attribute (`_mem._ENABLED`) and calls nothing else on the disabled
path — the tier-1 overhead guard enforces it.
"""
from __future__ import annotations

import threading
import time
import warnings
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from ..core import flags as _flags

__all__ = [
    "tag", "tag_of", "census", "census_ring", "top_buffers",
    "executable_memory", "phase_peaks", "phase_peak_ring",
    "is_oom", "maybe_dump_oom", "render_census", "reset",
]

# Hot-path gate: tag seams read this module attribute; one attribute load
# is the entire disabled-path cost (PR 1 monitor._ENABLED regime).
_ENABLED: bool = False

# id(buffer) -> (tag, origin, weakref). Keyed by id so registration never
# hashes (or pins) the array; the weakref's callback reaps the entry when
# the buffer is collected, so a donated-away buffer's tag dies with it and
# id reuse cannot mis-attribute a new buffer.
_TAGS: Dict[int, tuple] = {}

_LOCK = threading.RLock()
_CENSUS_RING: deque = deque(maxlen=16)
_PHASE_RING: deque = deque(maxlen=64)      # {"phase","ts","bytes"} samples
_PHASE_PEAKS: Dict[str, int] = {}          # phase -> max sampled bytes
_LEAK_HISTORY: Dict[str, deque] = {}       # tag -> trailing census bytes
_LEAK_WARNED: set = set()

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "fault injected at mem.alloc")


def _rewire(_v=None) -> None:
    global _ENABLED, _CENSUS_RING
    _ENABLED = bool(_flags.flag("mem_census"))
    ring = max(1, int(_flags.flag("mem_census_ring")))
    if ring != _CENSUS_RING.maxlen:
        with _LOCK:
            _CENSUS_RING = deque(list(_CENSUS_RING)[-ring:], maxlen=ring)


for _name in ("mem_census", "mem_census_ring"):
    _flags.watch_flag(_name, _rewire)
_rewire()


# ---- tagging ----------------------------------------------------------------

def _is_device_array(x) -> bool:
    """Concrete jax device array? Type check ONLY — never probe
    `addressable_shards` here: that property MATERIALIZES one child
    ArrayImpl per shard, each of which lands in `jax.live_arrays()` and
    double-counts every buffer the census touches."""
    import jax
    return isinstance(x, jax.Array) and not isinstance(x, jax.core.Tracer)


def _unwrap(leaf):
    """Tensor/_LazyValue -> device array (or the leaf itself). A device
    array is returned as-is — jax.Array also exposes a `_value` property
    (its cached NUMPY value), so unconditional unwrapping would silently
    swap the device buffer for a host copy."""
    if _is_device_array(leaf):
        return leaf
    v = getattr(leaf, "_value", None)
    if v is not None:
        leaf = v
        if _is_device_array(leaf):
            return leaf
    a = getattr(leaf, "_arr", None)
    if a is not None:
        leaf = a
    return leaf


def _iter_arrays(values):
    import jax
    for leaf in jax.tree_util.tree_leaves(values):
        arr = _unwrap(leaf)
        if not _is_device_array(arr):
            continue
        # Probe nbytes inside try/except: jax.Array ABC properties raise
        # NotImplementedError on extended dtypes (typed PRNG key arrays),
        # which hasattr does NOT swallow.
        try:
            if arr.nbytes >= 0:
                yield arr
        except Exception:
            # typed PRNG key arrays: the live buffer census sees their
            # underlying uint32 array, so tag that instead
            base = getattr(arr, "_base_array", None)
            try:
                if base is not None and base.nbytes >= 0:
                    yield base
            except Exception:
                continue


def _per_device_bytes(a):
    """(bytes_per_device, [device ids]) derived from the SHARDING, not
    from `a.addressable_shards` — see `_is_device_array`. Sharded arrays
    count 1/n per device, replicated arrays their full size on every
    device."""
    import numpy as np
    sharding = a.sharding
    devs = sorted(d.id for d in sharding.addressable_devices)
    shard_shape = sharding.shard_shape(a.shape)
    nb = int(np.prod(shard_shape, dtype=np.int64)) * int(a.dtype.itemsize)
    return nb, devs


def _buffer_key(a):
    """Dedup key: two ArrayImpls can alias ONE device buffer (a shard
    child materialized by some earlier `addressable_shards` walk aliases
    its parent) — count the underlying buffer once."""
    try:
        return a.unsafe_buffer_pointer()
    except Exception:
        return id(a)


def _reaper(key: int):
    def _cb(_ref):
        _TAGS.pop(key, None)
    return _cb


def tag(name: str, values: Any, origin: Optional[str] = None) -> int:
    """Tag every device array in `values` (any pytree of arrays / Tensors)
    as belonging to plane `name`. Returns the number of buffers tagged.
    Call sites re-tag replacement buffers after a donated dispatch commits
    — that is how tags survive donation."""
    if not _ENABLED:
        return 0
    n = 0
    name = str(name)
    for arr in _iter_arrays(values):
        key = id(arr)
        try:
            ref = weakref.ref(arr, _reaper(key))
        except TypeError:
            continue
        _TAGS[key] = (name, origin, ref)
        n += 1
    return n


def tag_of(arr) -> Optional[tuple]:
    """(tag, origin) for a tagged buffer, else None. Verifies the weakref
    still points at `arr` so a recycled id never mis-attributes."""
    entry = _TAGS.get(id(_unwrap(arr)))
    if entry is None:
        return None
    if entry[2]() is not _unwrap(arr):
        return None
    return entry[0], entry[1]


# ---- census -----------------------------------------------------------------

def census(publish: bool = True, store: bool = True) -> Dict[str, Any]:
    """Walk `jax.live_arrays()` and bucket live bytes per tag per device.
    Untagged buffers land in "other". Publishes `mem.<tag>.bytes` gauges
    (FLAGS_monitor), appends to the census ring, and feeds the leak watch
    unless told otherwise."""
    import jax
    tags: Dict[str, Dict[str, Any]] = {}
    total = 0
    # one row per underlying BUFFER: an aliasing ArrayImpl pair (parent +
    # materialized shard child) must count once, under its tag if either
    # alias carries one
    rows: Dict[Any, tuple] = {}
    for a in jax.live_arrays():
        try:
            if a.is_deleted():
                continue
            entry = _TAGS.get(id(a))
            name = entry[0] if entry is not None and entry[2]() is a \
                else None
            nb, devs = _per_device_bytes(a)
            key = _buffer_key(a)
            if key not in rows or name is not None:
                rows[key] = (name, nb, devs)
        except Exception:   # deleted/donated buffers race the walk
            continue
    for name, nb, devs in rows.values():
        bucket = tags.setdefault(name or "other",
                                 {"bytes": 0, "count": 0, "devices": {}})
        for did_ in devs:
            did = str(did_)
            bucket["bytes"] += nb
            bucket["devices"][did] = bucket["devices"].get(did, 0) + nb
            total += nb
        bucket["count"] += 1
    rec = {"ts": time.time(), "total_bytes": total, "tags": tags}
    if publish:
        from .. import monitor as _monitor
        if _monitor._ENABLED:
            for name, bucket in tags.items():
                _monitor.gauge_set(f"mem.{name}.bytes", bucket["bytes"])
            _monitor.gauge_set("mem.total.bytes", total)
    if store:
        with _LOCK:
            _CENSUS_RING.append(rec)
        _leak_check({n: b["bytes"] for n, b in tags.items()})
    return rec


def census_ring() -> List[Dict[str, Any]]:
    with _LOCK:
        return list(_CENSUS_RING)


def top_buffers(k: Optional[int] = None) -> List[Dict[str, Any]]:
    """The K largest live buffers, each with its tag + origin — the 'who
    owns the bytes' table of an OOM dump."""
    import jax
    if k is None:
        k = int(_flags.flag("mem_top_k"))
    rows: Dict[Any, dict] = {}
    for a in jax.live_arrays():
        try:
            if a.is_deleted():
                continue
            entry = _TAGS.get(id(a))
            tagged = entry is not None and entry[2]() is a
            key = _buffer_key(a)
            if key in rows and not tagged:   # keep the tagged alias
                continue
            rows[key] = {
                "bytes": int(a.nbytes),
                "shape": list(a.shape),
                "dtype": str(a.dtype),
                "tag": entry[0] if tagged else "other",
                "origin": entry[1] if tagged else None,
            }
        except Exception:
            continue
    out = sorted(rows.values(), key=lambda r: -r["bytes"])
    return out[:max(0, int(k))]


# ---- leak watch -------------------------------------------------------------

def _leak_check(per_tag: Dict[str, int]) -> None:
    window = int(_flags.flag("mem_leak_window"))
    if window <= 0:
        return
    from .. import monitor as _monitor
    with _LOCK:
        for name, nbytes in per_tag.items():
            hist = _LEAK_HISTORY.get(name)
            if hist is None or hist.maxlen != window + 1:
                hist = _LEAK_HISTORY[name] = deque(
                    list(hist or ()), maxlen=window + 1)
            hist.append(int(nbytes))
            if len(hist) < hist.maxlen:
                continue
            samples = list(hist)
            if all(a < b for a, b in zip(samples, samples[1:])):
                if _monitor._ENABLED:
                    _monitor.count("mem.leak_suspects")
                if name not in _LEAK_WARNED:
                    _LEAK_WARNED.add(name)
                    warnings.warn(
                        f"mem leak watch: tag '{name}' grew on {window} "
                        f"consecutive censuses ({samples[0]} -> "
                        f"{samples[-1]} bytes) — a held reference is "
                        "pinning HBM (FLAGS_mem_leak_window)",
                        ResourceWarning, stacklevel=2)


# ---- per-executable breakdown ----------------------------------------------

_MEM_ATTRS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)


def executable_memory(compiled) -> Dict[str, int]:
    """Normalized {argument_bytes, output_bytes, temp_bytes, alias_bytes,
    generated_code_bytes, peak_bytes} from an AOT-compiled executable's
    memory_analysis(). jax returns a CompiledMemoryStats object (attribute
    access) or, on some versions/backends, a dict or a one-element list;
    absent/failed analysis -> {}. `peak_bytes` approximates the
    executable's HBM high-water mark: arguments (minus donated aliases)
    + outputs + temps + program text."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if isinstance(ma, (list, tuple)):
        ma = ma[0] if ma else None
    if ma is None:
        return {}
    out: Dict[str, int] = {}
    for attr, norm in _MEM_ATTRS:
        v = ma.get(attr) if isinstance(ma, dict) else getattr(ma, attr, None)
        if isinstance(v, (int, float)):
            out[norm] = int(v)
    if out:
        out["peak_bytes"] = (out.get("argument_bytes", 0)
                             - out.get("alias_bytes", 0)
                             + out.get("output_bytes", 0)
                             + out.get("temp_bytes", 0)
                             + out.get("generated_code_bytes", 0))
    return out


# ---- peak-HBM per timeline phase -------------------------------------------

def _live_total() -> int:
    import jax
    total = 0
    seen = set()
    for a in jax.live_arrays():
        try:
            if a.is_deleted():
                continue
            key = _buffer_key(a)
            if key in seen:
                continue
            seen.add(key)
            total += int(a.nbytes)
        except Exception:
            continue
    return total


def on_phase(name: str, t0: float, t1: float) -> None:
    """StepTimeline phase-boundary hook (wired by obs._rewire when both the
    timeline and FLAGS_mem_census are on): sample total live bytes at each
    phase exit so the peak can be attributed to a phase."""
    if not _ENABLED:
        return
    nbytes = _live_total()
    with _LOCK:
        _PHASE_RING.append({"phase": name, "ts": t1, "bytes": nbytes})
        if nbytes > _PHASE_PEAKS.get(name, -1):
            _PHASE_PEAKS[name] = nbytes


def phase_peaks() -> Dict[str, int]:
    """phase -> max live bytes sampled at that phase's boundaries."""
    with _LOCK:
        return dict(_PHASE_PEAKS)


def phase_peak_ring() -> List[Dict[str, Any]]:
    with _LOCK:
        return list(_PHASE_RING)


# ---- OOM forensics ----------------------------------------------------------

def is_oom(exc: BaseException) -> bool:
    """XLA RESOURCE_EXHAUSTED (any backend's phrasing) or the fault-
    injected `mem.alloc` stand-in used to rehearse the path off-device."""
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def forensics(executables: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The memory section of an OOM dump: a fresh census (the last act
    before the artifact is written), the ring of prior censuses, the top-K
    buffers with tag + origin, per-phase peaks, and the per-executable
    breakdown the call site supplied."""
    try:
        current = census(publish=False, store=False)
    except Exception:
        current = {}
    return {
        "census": census_ring(),
        "census_at_dump": current,
        "top_buffers": top_buffers(),
        "phase_peaks": phase_peaks(),
        "executables": executables or {},
    }


def maybe_dump_oom(exc: BaseException, executable: Optional[str] = None,
                   report=None) -> Optional[str]:
    """Dispatch-site except-path: when `exc` is an OOM and the flight
    recorder is armed, write ONE rate-limited dump (reason "oom") whose
    extra.memory names the top buffers (tag + origin) and the owning
    executable's temp bytes. Stamps `exc.dump_path` like
    obs.dump_on_error. Returns the dump path or None."""
    if not is_oom(exc):
        return None
    from . import _FR_ENABLED, _RECORDER
    fr = _RECORDER
    if fr is None or not _FR_ENABLED:
        return None
    execs: Dict[str, Any] = {}
    if executable is not None and report is not None:
        try:
            execs[executable] = report() if callable(report) else dict(report)
        except Exception:
            execs[executable] = {}
    path = fr.dump(reason="oom", extra={"memory": forensics(execs)})
    if path:
        exc.dump_path = path  # type: ignore[attr-defined]
        if exc.args and isinstance(exc.args[0], str):
            exc.args = (exc.args[0] + f" [flight recorder: {path}]",) \
                + exc.args[1:]
    return path


# ---- rendering (monitor CLI `mem` subcommand) ------------------------------

def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GiB"


def render_census(rec: Dict[str, Any],
                  top: Optional[List[Dict[str, Any]]] = None) -> str:
    """Pretty-print one census record (+ optional top-buffer table)."""
    lines = ["-" * 72,
             f"memory census — total {_fmt_bytes(rec.get('total_bytes', 0))}",
             "-" * 72,
             f"{'Tag':<22}{'Bytes':>12}{'Share':>8}{'Buffers':>9}  Devices"]
    total = max(1, int(rec.get("total_bytes", 0)))
    tags = rec.get("tags", {})
    for name in sorted(tags, key=lambda n: -tags[n]["bytes"]):
        b = tags[name]
        devs = ",".join(sorted(b.get("devices", {}), key=int))
        lines.append(f"{name[:21]:<22}{_fmt_bytes(b['bytes']):>12}"
                     f"{b['bytes'] / total:>8.1%}{b.get('count', 0):>9}"
                     f"  [{devs}]")
    if top:
        lines.append("-" * 72)
        lines.append("top buffers:")
        for row in top:
            origin = f"  ({row['origin']})" if row.get("origin") else ""
            lines.append(f"  {_fmt_bytes(row['bytes']):>10}  "
                         f"{row['dtype']}{row['shape']}  "
                         f"tag={row['tag']}{origin}")
    lines.append("-" * 72)
    return "\n".join(lines)


# ---- test hygiene -----------------------------------------------------------

def reset() -> None:
    """Drop the side tables (tests): tags, rings, leak history."""
    with _LOCK:
        _TAGS.clear()
        _CENSUS_RING.clear()
        _PHASE_RING.clear()
        _PHASE_PEAKS.clear()
        _LEAK_HISTORY.clear()
        _LEAK_WARNED.clear()
