"""SLO objects — error-budget burn rate for the serving plane.

An SLO here is the pair (`FLAGS_slo_latency_ms`, `FLAGS_slo_target`):
"`target` of requests complete within `latency_ms`". Every finished
request is GOOD (status ok and fast enough) or BAD (over the latency
objective, rejected by admission, deadline-expired, or errored). The
error budget is the tolerated bad fraction, `1 - target`; the burn rate
over a window is

    burn(w) = bad_fraction(w) / (1 - target)

so burn 1.0 = consuming the budget exactly as provisioned, 14.4 = the
classic "page now" fast-burn threshold for a 0.999 SLO over an hour.
Multiple windows (`FLAGS_slo_windows`, default 60/300/3600s) are kept
simultaneously — the short window catches a fast burn while the long one
catches a slow leak — from one ring of per-second good/bad buckets.

Outputs, in priority order for the fleet tier (ROADMAP: load-aware
routing off the `'PDHQ'` probe):

  - `stats()` — the `"slo"` section of `ServingEngine.stats()` and hence
    the `'PDHQ'` wire probe: objective, per-window burn rates, good/bad
    totals, and latency quantiles from the `serving.e2e_latency` sketch
    (monitor.Histogram's DDSketch plane; <=1% relative error).
  - `slo.*` monitor gauges (`slo.burn.<w>s`, `slo.good`, `slo.bad`) —
    republished at most once a second from the record path, so a
    Prometheus scrape sees burn without anyone calling the probe.
  - `should_shed()` — optional admission hook: when the SHORTEST
    window's burn exceeds `FLAGS_slo_shed_burn`, `ServingEngine.submit`
    sheds new work as overloaded (burning a little budget deliberately
    now beats burning all of it in a brown-out).

Hot-path contract: `FLAGS_slo_latency_ms == 0` disables the plane —
`record_request()` is one module-attribute check, nothing else; the
tier-1 overhead guard enforces it.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..core import flags as _flags

__all__ = [
    "SloPlane", "enabled", "record_request", "burn_rates", "should_shed",
    "stats", "reset", "render_slo", "burn_from_gauges",
    "OUTCOME_OK", "OUTCOME_SLOW", "OUTCOME_REJECTED", "OUTCOME_DEADLINE",
    "OUTCOME_ERROR",
]

OUTCOME_OK = "ok"
OUTCOME_SLOW = "slow"            # completed, but over the latency objective
OUTCOME_REJECTED = "rejected"    # queue-full admission rejection (status 2)
OUTCOME_DEADLINE = "deadline"    # expired before completion (status 3)
OUTCOME_ERROR = "error"          # model/transport failure (status 1)

_BAD_OUTCOMES = (OUTCOME_SLOW, OUTCOME_REJECTED, OUTCOME_DEADLINE,
                 OUTCOME_ERROR)


def _parse_windows(spec: str) -> List[int]:
    out = []
    for part in str(spec).split(","):
        part = part.strip()
        if part:
            try:
                w = int(float(part))
            except ValueError:
                continue    # malformed flag value: fall back, don't raise
            if w > 0:
                out.append(w)
    return sorted(set(out)) or [60, 300, 3600]


class SloPlane:
    """One latency SLO + its burn-rate accounting: a ring of per-second
    (good, bad) buckets spanning the longest window, read at any window
    length. O(1) record, O(window) read."""

    def __init__(self, latency_ms: float, target: float,
                 windows: Optional[List[int]] = None,
                 shed_burn: float = 0.0):
        self.latency_ms = float(latency_ms)
        self.target = min(max(float(target), 0.0), 0.999999)
        self.windows = list(windows or [60, 300, 3600])
        self.shed_burn = float(shed_burn)
        self._budget = 1.0 - self.target
        self._horizon = max(self.windows)
        self._lock = threading.Lock()
        self._buckets: Dict[int, List[int]] = {}   # epoch-sec -> [good, bad]
        self._good_total = 0
        self._bad_total = 0
        self._bad_by_outcome: Dict[str, int] = {}
        self._last_publish = 0.0

    # -- write side --
    def record(self, latency_s: Optional[float],
               outcome: str = OUTCOME_OK,
               now: Optional[float] = None) -> bool:
        """Account one finished request. Returns True when it was BAD
        (callers use this to promote the request's trace to the
        protected ring). `now` is injectable for tests."""
        bad = outcome != OUTCOME_OK or (
            latency_s is not None and latency_s * 1e3 > self.latency_ms)
        if bad and outcome == OUTCOME_OK:
            outcome = OUTCOME_SLOW
        if now is None:
            now = time.time()
        sec = int(now)
        with self._lock:
            b = self._buckets.get(sec)
            if b is None:
                b = self._buckets[sec] = [0, 0]
                self._prune_locked(sec)
            b[1 if bad else 0] += 1
            if bad:
                self._bad_total += 1
                self._bad_by_outcome[outcome] = \
                    self._bad_by_outcome.get(outcome, 0) + 1
            else:
                self._good_total += 1
            publish = now - self._last_publish >= 1.0
            if publish:
                self._last_publish = now
        if publish:
            self._publish(now)
        return bad

    def _prune_locked(self, now_sec: int) -> None:
        floor = now_sec - self._horizon
        for sec in [s for s in self._buckets if s < floor]:
            del self._buckets[sec]

    # -- read side --
    def window_counts(self, window_s: int,
                      now: Optional[float] = None) -> Dict[str, int]:
        sec = int(now if now is not None else time.time())
        good = bad = 0
        with self._lock:
            for s, (g, b) in self._buckets.items():
                if sec - window_s < s <= sec:
                    good += g
                    bad += b
        return {"good": good, "bad": bad}

    def burn_rate(self, window_s: int,
                  now: Optional[float] = None) -> float:
        """bad_fraction / error_budget over the window; 0.0 when the
        window saw no traffic (no news is not a page)."""
        c = self.window_counts(window_s, now)
        total = c["good"] + c["bad"]
        if total == 0:
            return 0.0
        return (c["bad"] / total) / self._budget

    def burn_rates(self, now: Optional[float] = None) -> Dict[int, float]:
        return {w: self.burn_rate(w, now) for w in self.windows}

    def should_shed(self, now: Optional[float] = None) -> bool:
        """Admission hook: True when the shortest window burns faster
        than FLAGS_slo_shed_burn allows (0 = never shed)."""
        if self.shed_burn <= 0.0:
            return False
        return self.burn_rate(min(self.windows), now) > self.shed_burn

    def _publish(self, now: float) -> None:
        from .. import monitor as _monitor
        if not _monitor._ENABLED:
            return
        for w, rate in self.burn_rates(now).items():
            _monitor.gauge_set(f"slo.burn.{w}s", rate)
        _monitor.gauge_set("slo.good", self._good_total)
        _monitor.gauge_set("slo.bad", self._bad_total)
        # objective gauges make a snapshot export self-describing — the
        # monitor CLI `slo` subcommand rebuilds the doc from gauges alone
        _monitor.gauge_set("slo.objective.latency_ms", self.latency_ms)
        _monitor.gauge_set("slo.objective.target", self.target)

    def stats(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The 'slo' section of engine stats / the 'PDHQ' probe."""
        from .. import monitor as _monitor
        qs = _monitor.histogram("serving.e2e_latency").quantiles()
        with self._lock:
            good, bad = self._good_total, self._bad_total
            by_outcome = dict(self._bad_by_outcome)
        return {
            "objective": {"latency_ms": self.latency_ms,
                          "target": self.target},
            "burn": {str(w): round(r, 4)
                     for w, r in self.burn_rates(now).items()},
            "good": good,
            "bad": bad,
            "bad_by_outcome": by_outcome,
            "shedding": self.should_shed(now),
            "latency_ms": {f"p{int(q * 100)}": v * 1e3
                           for q, v in qs.items()},
        }

    def reset(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._good_total = 0
            self._bad_total = 0
            self._bad_by_outcome.clear()
            self._last_publish = 0.0


# ---- module plane (flag-wired singleton) ------------------------------------

_ENABLED: bool = False
_PLANE: Optional[SloPlane] = None


def _rewire(_v=None) -> None:
    global _ENABLED, _PLANE
    latency_ms = float(_flags.flag("slo_latency_ms"))
    if latency_ms <= 0.0:
        _ENABLED = False
        _PLANE = None
        return
    _PLANE = SloPlane(latency_ms, float(_flags.flag("slo_target")),
                      _parse_windows(_flags.flag("slo_windows")),
                      float(_flags.flag("slo_shed_burn")))
    _ENABLED = True


for _name in ("slo_latency_ms", "slo_target", "slo_windows",
              "slo_shed_burn"):
    _flags.watch_flag(_name, _rewire)
_rewire()


def enabled() -> bool:
    return _ENABLED


def plane() -> Optional[SloPlane]:
    return _PLANE


def record_request(latency_s: Optional[float],
                   outcome: str = OUTCOME_OK) -> bool:
    """Account one finished serving request (engine hot path — callers
    guard on `_slo._ENABLED` so the disabled plane costs one attribute
    check). Returns True when the request was BAD for the SLO."""
    p = _PLANE
    if p is None:
        return False
    return p.record(latency_s, outcome)


def burn_rates() -> Dict[int, float]:
    p = _PLANE
    return p.burn_rates() if p is not None else {}


def should_shed() -> bool:
    p = _PLANE
    return p.should_shed() if p is not None else False


def stats() -> Optional[Dict[str, Any]]:
    p = _PLANE
    return p.stats() if p is not None else None


def reset() -> None:
    if _PLANE is not None:
        _PLANE.reset()


def shortest_window_burn(stats_doc: Optional[Dict[str, Any]]) -> float:
    """Burn rate of the SHORTEST window in a `stats()`-shaped doc (keys
    are `str(window_seconds)`). The fastest-reacting window is the fleet
    tier's routing/canary signal — it spikes on a fresh error burst long
    before the long windows move. 0.0 on a missing/empty/garbled doc: a
    replica that reports no SLO section routes on queue alone."""
    if not isinstance(stats_doc, dict):
        return 0.0
    windows = stats_doc.get("burn") or {}
    try:
        return float(windows[min(windows, key=lambda w: int(w))])
    except (ValueError, TypeError, KeyError):
        return 0.0


def burn_from_gauges(gauges: Optional[Dict[str, Any]]) -> float:
    """Shortest-window burn straight off `slo.burn.<w>s` monitor gauges
    (the shape a TelemetryCollector source record carries). The fleet
    signal must be the per-source WORST of these — summing burn gauges
    across sources (what merge_snapshots does to gauges) inflates the
    rate by the source count. 0.0 on a missing/garbled doc."""
    if not isinstance(gauges, dict):
        return 0.0
    burns: Dict[int, float] = {}
    for name, val in gauges.items():
        if name.startswith("slo.burn.") and name.endswith("s"):
            try:
                burns[int(name[len("slo.burn."):-1])] = float(val)
            except (ValueError, TypeError):
                continue
    if not burns:
        return 0.0
    return burns[min(burns)]


# ---- rendering (monitor CLI `slo` subcommand) -------------------------------

def doc_from_snapshot(snap: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Rebuild an slo stats doc from a monitor snapshot export's `slo.*`
    gauges + the serving.e2e_latency histogram quantiles. Returns None
    when the snapshot carries no SLO gauges (plane never configured)."""
    gauges = snap.get("gauges", {})
    burn = {}
    for name, val in gauges.items():
        if name.startswith("slo.burn.") and name.endswith("s"):
            try:
                burn[name[len("slo.burn."):-1]] = float(val)
            except ValueError:
                continue
    if not burn and "slo.good" not in gauges:
        return None
    hist = (snap.get("histograms") or {}).get("serving.e2e_latency", {})
    lat = {k: hist[k] * 1e3 for k in ("p50", "p95", "p99") if k in hist}
    return {
        "objective": {
            "latency_ms": gauges.get("slo.objective.latency_ms", 0.0),
            "target": gauges.get("slo.objective.target", 0.0),
        },
        "burn": burn,
        "good": gauges.get("slo.good", 0),
        "bad": gauges.get("slo.bad", 0),
        "bad_by_outcome": {},
        "shedding": False,
        "latency_ms": lat,
    }


def render_slo(doc: Optional[Dict[str, Any]]) -> str:
    if not doc:
        return ("(no SLO configured — set FLAGS_slo_latency_ms / "
                "FLAGS_slo_target)")
    obj = doc.get("objective", {})
    lines = ["-" * 78,
             f"SLO: {obj.get('target', 0.0) * 100:.3f}% of requests within "
             f"{obj.get('latency_ms', 0.0):.1f}ms"
             + ("   [SHEDDING]" if doc.get("shedding") else ""),
             "-" * 78]
    burn = doc.get("burn", {})
    if burn:
        lines.append("burn rate (1.0 = consuming budget exactly):")
        for w in sorted(burn, key=lambda x: int(x)):
            rate = float(burn[w])
            flag = "  <-- fast burn" if rate > 10.0 else \
                ("  <-- over budget" if rate > 1.0 else "")
            lines.append(f"  {int(w):>6}s window: {rate:8.3f}{flag}")
    good, bad = doc.get("good", 0), doc.get("bad", 0)
    total = good + bad
    frac = (bad / total * 100.0) if total else 0.0
    lines.append(f"requests: {total} total, {bad} bad ({frac:.3f}%)")
    by = doc.get("bad_by_outcome", {})
    if by:
        lines.append("  bad by outcome: " + ", ".join(
            f"{k}={v}" for k, v in sorted(by.items(), key=lambda kv: -kv[1])))
    lat = doc.get("latency_ms", {})
    if lat:
        lines.append("e2e latency (sketch, <=1% rel err): " + "  ".join(
            f"{k}={lat[k]:.2f}ms" for k in ("p50", "p95", "p99")
            if k in lat))
    lines.append("-" * 78)
    return "\n".join(lines)
