"""paddle_tpu.obs — step-timeline attribution + black-box flight recorder.

The observability plane on top of `monitor.py` (PR 1's flat counters/spans):

  - `StepTimeline` (`obs/timeline.py`): per-step phase records (data_wait,
    h2d, trace_compile, device_compute via block_until_ready fencing,
    collective, optimizer, snapshot/guard overhead) threaded through
    `jit/train_step.py`, `parallel/spmd.py`, the `io/` DataLoader,
    `optimizer/`, and `guard/`; bounded ring; chrome-trace export merged
    with Profiler events. Gate: `FLAGS_obs_timeline`.
  - `FlightRecorder` (`obs/recorder.py`): black-box rings (step records,
    monitor-counter deltas, collective sequence, guard/fault events) with
    `dump(path, reason)`; automatic dumps registered per guard error type
    (`register_dump_trigger`) fire from the watchdog, desync detector,
    divergence guard, serving overload, and SIGTERM preemption.
    Gate: `FLAGS_obs_flight_recorder`.
  - cross-rank merge (`obs/merge.py`): rank-stamped timelines gathered
    through the rendezvous store into one pod timeline naming the
    straggler rank per phase (`TrainGuard.timeline_report()`).
  - XLA cost analysis (`obs/cost.py`): compiler-attributed FLOPs/bytes per
    executable -> attributed MFU and roofline gap in `bench.py`.

Hot-path contract (same as monitor/faults/lint): instrumented sites check
ONE module attribute (`_obs._TL_ENABLED` / `_obs._FR_ENABLED` /
`_obs._ENABLED`) and call nothing else on the disabled path — the tier-1
overhead guard enforces it.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Type

from ..core import flags as _flags
from . import memory  # noqa: F401  (the HBM attribution plane)
from . import slo  # noqa: F401  (error-budget burn rate plane)
from . import trace  # noqa: F401  (request-scoped distributed tracing)
from .cost import attributed_mfu, executable_cost, roofline_gap  # noqa: F401
from .memory import (census, executable_memory, maybe_dump_oom,  # noqa: F401
                     top_buffers)
from .merge import (gather_timelines, merge_timelines,  # noqa: F401
                    slim_records, straggler_report)
from .recorder import (DUMP_SCHEMA, FlightRecorder,  # noqa: F401
                       dump_to_chrome_events)
from .timeline import NULL_CTX, PHASES, StepTimeline  # noqa: F401
from . import telemetry  # noqa: F401  (push-based fleet telemetry plane)

__all__ = [
    "StepTimeline", "FlightRecorder", "PHASES", "DUMP_SCHEMA",
    "enabled", "enable", "disable", "timeline", "recorder",
    "phase", "step_record", "add_phase", "add_async_phase", "mark",
    "record_event", "record_collective",
    "dump", "dump_on_error", "register_dump_trigger", "dump_triggers",
    "trigger_reason", "gather_timelines", "merge_timelines",
    "straggler_report", "slim_records", "executable_cost",
    "attributed_mfu", "roofline_gap", "dump_to_chrome_events",
    "memory", "census", "top_buffers", "executable_memory",
    "maybe_dump_oom", "trace", "slo", "telemetry",
]

# ---- gates + singletons ----------------------------------------------------
# Instrumented call sites read these module attributes directly; watch_flag
# keeps them in sync with paddle.set_flags. The timeline singleton exists
# whenever either plane is on (the recorder reads its rings).

_TL_ENABLED: bool = False
_FR_ENABLED: bool = False
_ENABLED: bool = False   # either plane on (sites that feed both check this)

_TIMELINE: Optional[StepTimeline] = None
_RECORDER: Optional[FlightRecorder] = None


def _rewire() -> None:
    global _TL_ENABLED, _FR_ENABLED, _ENABLED, _TIMELINE, _RECORDER
    tl_on = bool(_flags.flag("obs_timeline"))
    fr_on = bool(_flags.flag("obs_flight_recorder"))
    if (tl_on or fr_on) and _TIMELINE is None:
        _TIMELINE = StepTimeline(capacity=int(_flags.flag("obs_ring_steps")))
    if fr_on and _RECORDER is None and _TIMELINE is not None:
        _RECORDER = FlightRecorder(
            _TIMELINE, snapshot_ring=int(_flags.flag("obs_ring_snapshots")))
    if _TIMELINE is not None:
        _TIMELINE.on_close = _RECORDER.on_step_end if (fr_on and _RECORDER) \
            else None
        # peak-HBM per phase: sample total live bytes at phase boundaries
        # when the memory plane is also on (obs/memory.on_phase)
        _TIMELINE.on_phase = memory.on_phase if (tl_on and memory._ENABLED) \
            else None
    _TL_ENABLED = tl_on
    _FR_ENABLED = fr_on
    _ENABLED = tl_on or fr_on


for _name in ("obs_timeline", "obs_flight_recorder", "mem_census"):
    _flags.watch_flag(_name, lambda _v: _rewire())
_rewire()


def enabled() -> bool:
    return _ENABLED


def enable(timeline: bool = True, flight_recorder: bool = True) -> None:
    _flags.set_flags({"obs_timeline": timeline,
                      "obs_flight_recorder": flight_recorder})


def disable() -> None:
    _flags.set_flags({"obs_timeline": False, "obs_flight_recorder": False})


def reset() -> None:
    """Drop the singletons (tests): a fresh enable() starts clean rings."""
    global _TIMELINE, _RECORDER
    _TIMELINE = None
    _RECORDER = None
    _rewire()


def timeline() -> StepTimeline:
    """The process StepTimeline (created on first use even when disabled,
    so read-side tooling never needs a flag check)."""
    global _TIMELINE
    if _TIMELINE is None:
        _TIMELINE = StepTimeline(capacity=int(_flags.flag("obs_ring_steps")))
        _rewire()
    return _TIMELINE


def recorder() -> FlightRecorder:
    global _RECORDER
    if _RECORDER is None:
        _RECORDER = FlightRecorder(
            timeline(), snapshot_ring=int(_flags.flag("obs_ring_snapshots")))
        _rewire()
    return _RECORDER


# ---- instrumentation entry points (the threaded call sites use these) ------

def phase(name: str):
    """`with obs.phase("h2d"): ...` — folds the duration into the open step
    record (or the between-steps bucket). Disabled -> shared no-op ctx."""
    tl = _TIMELINE
    if tl is None or not _TL_ENABLED:
        return NULL_CTX
    return tl.phase(name)


def step_record():
    """Open (or join — reentrant) the per-step record around one training
    step. Disabled -> shared no-op ctx."""
    tl = _TIMELINE
    if tl is None or not _TL_ENABLED:
        return NULL_CTX
    return tl.step_record()


def in_phase() -> bool:
    """True when the calling thread is already inside an open `phase()`
    context. `core/executable.py` uses this to book a dispatch exactly
    once: an inner site that finds itself nested skips opening a second
    phase (the enclosing one already owns the wall time)."""
    from .timeline import thread_phase_depth
    return thread_phase_depth() > 0


def add_phase(name: str, dur: float, t0=None, t1=None) -> None:
    tl = _TIMELINE
    if tl is not None and _TL_ENABLED:
        tl.add_phase(name, dur, t0, t1)


def add_async_phase(name: str, dur: float, t0=None, t1=None) -> None:
    """Book concurrent (hidden) work — always into the between-steps
    bucket, never the open step record (see StepTimeline.add_async_phase)."""
    tl = _TIMELINE
    if tl is not None and _TL_ENABLED:
        tl.add_async_phase(name, dur, t0, t1)


def mark(name: str) -> None:
    tl = _TIMELINE
    if tl is not None and _ENABLED:
        tl.mark(name)


def record_event(kind: str, **payload) -> None:
    fr = _RECORDER
    if fr is not None and _FR_ENABLED:
        fr.record_event(kind, **payload)


def record_collective(name: str, nbytes: int) -> None:
    fr = _RECORDER
    if fr is not None and _FR_ENABLED:
        fr.record_collective(name, nbytes)


def dump(path: Optional[str] = None, reason: str = "manual",
         extra: Optional[Dict[str, Any]] = None,
         incident_id: Optional[str] = None,
         source: Optional[str] = None) -> Optional[str]:
    """Dump the flight recorder (even if the flag is off — an explicit call
    is an explicit request; the rings are just emptier)."""
    return recorder().dump(path=path, reason=reason, extra=extra,
                           incident_id=incident_id, source=source)


# ---- automatic dump triggers ------------------------------------------------
# Failure types that must produce a black-box artifact register here; a
# tier-1 test walks GuardError's subclass tree and fails on any class with
# no trigger (directly or via a registered ancestor) — a future guard error
# without forensics fails CI, not a postmortem.

_DUMP_TRIGGERS: Dict[Type[BaseException], str] = {}


def register_dump_trigger(exc_cls: Type[BaseException], reason: str) -> None:
    _DUMP_TRIGGERS[exc_cls] = reason


def dump_triggers() -> Dict[Type[BaseException], str]:
    return dict(_DUMP_TRIGGERS)


def trigger_reason(exc_cls: Type[BaseException]) -> Optional[str]:
    """Registered dump reason for an error type, walking its MRO (so a
    subclass of a registered error inherits the trigger)."""
    for klass in exc_cls.__mro__:
        if klass in _DUMP_TRIGGERS:
            return _DUMP_TRIGGERS[klass]
    return None


def dump_on_error(exc: BaseException,
                  extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Automatic-dump path for raise sites: when the flight recorder is
    armed and exc's type has a registered trigger, dump (rate-limited per
    reason), stamp `exc.dump_path`, and append the path to the error
    message so the operator's traceback names the artifact."""
    fr = _RECORDER
    if fr is None or not _FR_ENABLED:
        return None
    reason = trigger_reason(type(exc))
    if reason is None:
        return None
    path = fr.dump(reason=reason, extra=extra)
    if path:
        exc.dump_path = path  # type: ignore[attr-defined]
        if exc.args and isinstance(exc.args[0], str):
            exc.args = (exc.args[0] + f" [flight recorder: {path}]",) \
                + exc.args[1:]
    return path
