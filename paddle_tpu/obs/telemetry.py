"""Push-based fleet telemetry plane — the cluster-visible monitor tier.

Every observability plane before this one is per-process: the FleetRouter
polls 'PDHQ' on a multi-second interval, fleet-wide p99 does not exist
(per-replica p99s cannot be averaged), and an incident on one replica
produces one blind local dump. This module closes all three gaps:

  - `TelemetryExporter` — one per process (ReplicaAgent, PS primary and
    standby, trainers under TrainGuard). Ships (a) delta-compressed
    monitor counters, (b) mergeable DDSketch histograms
    (`monitor.Histogram.merge()` — bin-wise sums, so the collector's
    fleet quantiles keep the sketch's <=1% bound), and (c) an immediate
    event channel (death, drain, rollout, lease_expiry, guard
    divergence/stall, oom, slo_burn, dump) over CRC-framed 'PDTM' pushes
    with a `telemetry.push` fault site. Events buffer into a bounded
    drop-oldest ring: a SIGKILLed collector costs telemetry
    (`telemetry.dropped` counts exactly what), never serving throughput.
  - `TelemetryCollector` — discovered via the existing TCPStore
    rendezvous (`telemetry:{fleet}:collector`). Bounded time-series ring
    per (source, metric), ONE fleet-wide Prometheus scrape
    (`monitor.prometheus_text_multi`: `source=` labels + merged-sketch
    quantile families), the live `python -m paddle_tpu.monitor top`
    fleet table (stragglers via obs/merge.py skew logic), threshold +
    multi-window-burn alert rules (obs/slo.py semantics), and correlated
    incidents: any dump-triggering error fans a dump command to every
    live source under one shared `incident_id`, so a desync yields
    time-aligned flight-recorder dumps from the whole fleet.
  - Push-fed death detection: a SIGKILL closes the exporter's socket,
    the collector's connection reader sees EOF immediately, and a
    subscribed FleetRouter marks the replica dead in well under a
    second — no waiting out the lease TTL or the poll interval (both are
    retained as fallback).

Wire protocol ('PDHQ'/CMD_REPLICATE style, but CRC-framed — see
utils/net.py): exporter sends 'PDTM' frames whose JSON body is
{"op": hello|metrics|events|query|bye, ...}; the collector answers each
with a 'PDTA' ack {"ok": true, "commands": [...]} that doubles as its
command channel (incident dump fan-out rides the acks).

Gate: `FLAGS_telemetry`. Off = zero telemetry threads and sockets.
"""
from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
import uuid
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import faults as _faults
from .. import monitor as _monitor
from ..core import flags as _flags
from ..utils import net as _net
from ..utils import syncwatch as _syncwatch

__all__ = ["TelemetryExporter", "TelemetryCollector", "emit",
           "get_default", "query_collector", "render_top"]

# live exporters/collectors — the conftest leak fixture reaps stragglers
_LIVE: "weakref.WeakSet" = weakref.WeakSet()

# the process-default exporter (recorder.dump and guard sites emit here)
_DEFAULT: Optional["TelemetryExporter"] = None

_IO_TIMEOUT_S = 5.0


def emit(kind: str, **detail) -> None:
    """Fire an event on the process-default exporter; no-op without one
    (one module-attribute read on the disabled path)."""
    exp = _DEFAULT
    if exp is not None:
        exp.event(kind, **detail)


def get_default() -> Optional["TelemetryExporter"]:
    return _DEFAULT


def _store_key(fleet: str) -> str:
    return f"telemetry:{fleet}:collector"


def _safe_name(s: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "-", str(s))


def _now() -> float:
    return time.time()


# ---------------------------------------------------------------------------
# exporter
# ---------------------------------------------------------------------------

class TelemetryExporter:
    """Per-process telemetry pusher. One background thread owns every
    socket operation: `event()` (callable from any thread, including the
    serving hot path) only appends to a bounded deque and sets a wake
    flag — it can never block on the network or a dead collector."""

    def __init__(self, store, source: str, role: str = "process",
                 fleet: str = "default",
                 meta: Optional[Dict[str, Any]] = None,
                 interval_s: Optional[float] = None):
        self.store = store
        self.source = str(source)
        self.role = role
        self.fleet = fleet
        self.meta = dict(meta or {})
        self.interval_s = float(interval_s
                                if interval_s is not None
                                else _flags.flag("telemetry_interval_s"))
        self._events: deque = deque(
            maxlen=max(1, int(_flags.flag("telemetry_buffer"))))
        self._lock = _syncwatch.lock("telemetry.TelemetryExporter._lock")
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the telemetry plane's substrate channel: resolver re-discovers
        # the collector through the store on every (re)connect, the
        # legacy `telemetry.push` fault site keeps firing alongside
        # `net.telemetry.send`
        self._chan = _net.RpcChannel(
            "telemetry", resolver=self._resolve,
            connect_timeout=_IO_TIMEOUT_S,
            legacy_sites=("telemetry.push", None))
        self._addr: Optional[Tuple[str, int]] = None
        self._need_hello = True
        self._last_counters: Dict[str, Any] = {}
        # own tallies (tests read these without the monitor flag on)
        self.pushes = 0
        self.dropped = 0
        self.reconnects = 0

    # -- lifecycle --
    def start(self) -> "TelemetryExporter":
        global _DEFAULT
        if self._thread is not None:
            return self
        self._thread = _syncwatch.Thread(
            target=self._run, name=f"telemetry-export-{self.source}",
            daemon=True)
        self._thread.start()
        _LIVE.add(self)
        if _DEFAULT is None:
            _DEFAULT = self
        return self

    def stop(self, timeout: float = 5.0) -> None:
        global _DEFAULT
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None
        self._close_sock()
        if _DEFAULT is self:
            _DEFAULT = None

    close = stop

    # -- producers (any thread) --
    def event(self, kind: str, **detail) -> None:
        """Queue an immediate-push event. Drop-oldest under overflow:
        losing the oldest buffered event to a dead collector is the
        designed cost; blocking the caller never is."""
        ev = {"kind": str(kind), "ts": _now(), "detail": detail}
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
                if _monitor._ENABLED:
                    _monitor.count("telemetry.dropped")
            self._events.append(ev)
        self._wake.set()

    # -- export thread --
    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                break
            self._flush()
        # final best-effort flush + graceful goodbye (a 'bye' lets the
        # collector tell shutdown from death)
        self._flush(final=True)
        self._close_sock()

    def _close_sock(self) -> None:
        self._chan.drop()
        self._need_hello = True

    def _resolve(self) -> List[Tuple[str, int]]:
        addr = self._discover()
        return [addr] if addr is not None else []

    def _discover(self) -> Optional[Tuple[str, int]]:
        try:
            raw = self.store.get(_store_key(self.fleet))
        except Exception:
            return None  # not published yet (KeyError) or store gone
        if isinstance(raw, bytes):
            raw = raw.decode()
        parts = str(raw).split()
        if len(parts) != 2:
            return None
        try:
            return parts[0], int(parts[1])
        except ValueError:
            return None

    def _ensure_conn(self) -> bool:
        if self._chan.connected and not self._need_hello:
            return True
        addr = self._discover()
        if addr is None:
            return False
        if not self._chan.connected or addr != self._addr:
            self._close_sock()
            try:
                self._chan.connect()
            except OSError:
                return False
            self._addr = addr
        try:
            self._exchange({"op": "hello", "source": self.source,
                            "role": self.role, "pid": os.getpid(),
                            "meta": self.meta})
        except Exception:
            self._close_sock()
            return False
        self._need_hello = False
        # a (re)connect invalidates the delta baseline: resend absolutes
        self._last_counters = {}
        return True

    def _exchange(self, body: Dict[str, Any]) -> Dict[str, Any]:
        self._chan.check_send_faults()
        if not self._chan.connected:
            raise ConnectionError("no collector connection")
        sock = self._chan.sock
        _net.send_crc_frame(sock, _net.PDTM_MAGIC,
                            json.dumps(body, default=str).encode())
        self._chan.check_recv_faults()
        ack = json.loads(_net.recv_crc_frame(
            sock, _net.PDTA_MAGIC,
            deadline=time.monotonic() + _IO_TIMEOUT_S))
        self.pushes += 1
        if _monitor._ENABLED:
            _monitor.count("telemetry.pushes")
        for cmd in ack.get("commands") or []:
            try:
                self._handle_command(cmd)
            except Exception:
                pass  # a bad command must not kill the export loop
        return ack

    def _flush(self, final: bool = False) -> None:
        with self._lock:
            events = list(self._events)
            self._events.clear()
        try:
            if not self._ensure_conn():
                raise ConnectionError("collector unavailable")
            snap = _monitor.mergeable_snapshot()
            counters = snap["counters"]
            delta = {k: v - self._last_counters.get(k, 0)
                     for k, v in counters.items()
                     if v != self._last_counters.get(k, 0)}
            full = not self._last_counters
            self._exchange({"op": "metrics", "source": self.source,
                            "full": full,
                            "counters": counters if full else delta,
                            "gauges": snap["gauges"],
                            "histograms": snap["histograms"]})
            self._last_counters = counters
            if events:
                self._exchange({"op": "events", "source": self.source,
                                "events": events})
            if final:
                self._exchange({"op": "bye", "source": self.source})
        except Exception:
            # network failure (or injected telemetry.push fault): drop
            # the connection, re-buffer the drained events (drop-oldest
            # still bounds them), and let the next tick retry
            had_conn = self._chan.connected
            self._close_sock()
            if had_conn:
                self.reconnects += 1
                if _monitor._ENABLED:
                    _monitor.count("telemetry.reconnects")
            if events and not final:
                with self._lock:
                    room = self._events.maxlen - len(self._events)
                    lost = max(0, len(events) - room)
                    if lost:
                        self.dropped += lost
                        if _monitor._ENABLED:
                            _monitor.count("telemetry.dropped", lost)
                    for ev in events[lost:][::-1]:
                        self._events.appendleft(ev)

    # -- collector commands (ride the acks) --
    def _handle_command(self, cmd: Dict[str, Any]) -> None:
        if not isinstance(cmd, dict):
            return
        if cmd.get("op") == "dump":
            iid = str(cmd.get("incident_id") or "incident")
            reason = str(cmd.get("reason") or "incident")
            from . import dump as _dump
            d = str(_flags.flag("obs_dump_dir")) or "flight_recorder"
            # EXPLICIT path: an incident dump must never be suppressed by
            # the per-reason rate limiter (the whole point is every
            # member dumping at once)
            path = os.path.join(
                d, f"flightrec_{_safe_name(iid)}_"
                   f"{_safe_name(self.source)}.json")
            _dump(path=path, reason=reason, incident_id=iid,
                  source=self.source)


# ---------------------------------------------------------------------------
# collector
# ---------------------------------------------------------------------------

class TelemetryCollector:
    """The fleet's one aggregation point. Accepts 'PDTM' pushes, keeps a
    bounded ring per (source, metric), serves the fleet-wide scrape and
    `monitor top` doc, evaluates alert rules, relays events to
    subscribers (the FleetRouter fast path), and fans out correlated
    incident dump commands."""

    def __init__(self, store, fleet: str = "default",
                 host: str = "127.0.0.1", port: int = 0):
        self.store = store
        self.fleet = fleet
        self.host = host
        self.port = port
        self._lock = _syncwatch.lock("telemetry.TelemetryCollector._lock")
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        ring = max(4, int(_flags.flag("telemetry_ring")))
        self._ring = ring
        # per-source state: meta/role/pid, reconstructed-absolute
        # counters, gauges, histogram payloads, liveness
        self.sources: Dict[str, Dict[str, Any]] = {}
        self.series: Dict[Tuple[str, str], deque] = {}
        self.events: deque = deque(maxlen=ring)
        self._commands: Dict[str, deque] = {}
        self._subscribers: List[Callable[[Dict[str, Any]], None]] = []
        self._rules: List[Dict[str, Any]] = []
        self._active_alerts: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.burn_threshold = 1.0   # multi-window burn rule (obs/slo.py)
        self._pool: Dict[str, Any] = {}   # autoscaler pool state
        self.incidents: Dict[str, Dict[str, Any]] = {}
        self._last_incident = 0.0
        self._conn_seq = 0

    # -- lifecycle --
    def start(self) -> "TelemetryCollector":
        if self._listener is not None:
            return self
        srv = _net.make_listener(self.host, self.port, backlog=64)
        # poll-style accept: closing a listener does not reliably wake a
        # thread blocked in accept(), so the loop must time out to see
        # the stop flag
        srv.settimeout(0.2)
        self.port = srv.getsockname()[1]
        self._listener = srv
        t = _syncwatch.Thread(target=self._accept_loop,
                             name="telemetry-accept", daemon=True)
        t.start()
        self._threads.append(t)
        r = _syncwatch.Thread(target=self._reap_loop,
                             name="telemetry-reap", daemon=True)
        r.start()
        self._threads.append(r)
        # publish the rendezvous record LAST: a discoverable collector
        # is an accepting collector
        self.store.set(_store_key(self.fleet), f"{self.host} {self.port}")
        _LIVE.add(self)
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        try:  # stop advertising (the store has no delete)
            self.store.set(_store_key(self.fleet), b"")
        except Exception:
            pass
        srv, self._listener = self._listener, None
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout)
        self._threads = []

    close = stop

    # -- ingest --
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                srv = self._listener
                if srv is None:
                    return
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            try:
                conn = _net.secure_server(conn, "telemetry")
            except (_net.AuthError, OSError, ValueError):
                continue  # unauthenticated peer: counted + dropped
            conn.settimeout(None)
            with self._lock:
                self._conns.append(conn)
                self._conn_seq += 1
                cid = self._conn_seq
            t = _syncwatch.Thread(target=self._conn_loop,
                                 args=(conn, cid),
                                 name=f"telemetry-conn-{cid}", daemon=True)
            t.start()
            self._threads.append(t)

    def _conn_loop(self, conn: socket.socket, cid: int) -> None:
        src: Optional[str] = None
        graceful = False
        try:
            while not self._stop.is_set():
                body = json.loads(_net.recv_crc_frame(
                    conn, _net.PDTM_MAGIC))
                op = body.get("op")
                if op == "hello":
                    src = str(body.get("source"))
                    self._on_hello(src, cid, body)
                elif op == "metrics" and src is not None:
                    self._on_metrics(src, body)
                elif op == "events" and src is not None:
                    for ev in body.get("events") or []:
                        self._dispatch_event(src, ev)
                elif op == "bye":
                    graceful = True
                elif op == "query":
                    _net.send_crc_frame(
                        conn, _net.PDTA_MAGIC,
                        json.dumps({"ok": True, "doc": self.snapshot_doc()},
                                   default=str).encode())
                    continue
                cmds = self._drain_commands(src) if src else []
                _net.send_crc_frame(
                    conn, _net.PDTA_MAGIC,
                    json.dumps({"ok": True, "commands": cmds}).encode())
                if graceful:
                    break
        except (ConnectionError, ValueError, OSError, json.JSONDecodeError,
                TimeoutError):
            pass  # EOF / corrupt frame / teardown — handled below
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            if src is not None:
                self._on_disconnect(src, cid, graceful)

    def _on_hello(self, src: str, cid: int, body: Dict[str, Any]) -> None:
        with self._lock:
            rec = self.sources.setdefault(src, {
                "counters": {}, "gauges": {}, "histograms": {}})
            rec.update({"role": body.get("role"), "pid": body.get("pid"),
                        "meta": body.get("meta") or {}, "alive": True,
                        "graceful": False, "conn_id": cid,
                        "last_seen": _now()})

    def _on_metrics(self, src: str, body: Dict[str, Any]) -> None:
        ts = _now()
        with self._lock:
            rec = self.sources.setdefault(src, {
                "counters": {}, "gauges": {}, "histograms": {},
                "alive": True, "meta": {}})
            if body.get("full"):
                rec["counters"] = dict(body.get("counters") or {})
            else:
                for k, d in (body.get("counters") or {}).items():
                    rec["counters"][k] = rec["counters"].get(k, 0) + d
            rec["gauges"] = dict(body.get("gauges") or {})
            rec["histograms"] = dict(body.get("histograms") or {})
            rec["last_seen"] = ts
            rec["alive"] = True
            for k, v in rec["counters"].items():
                self._series_append(src, k, ts, v)
            for k, v in rec["gauges"].items():
                self._series_append(src, k, ts, v)
            for k, h in rec["histograms"].items():
                if isinstance(h, dict) and "count" in h:
                    self._series_append(src, k + ".count", ts, h["count"])
        self._eval_rules(src)

    def _series_append(self, src, metric, ts, value) -> None:
        # caller holds self._lock
        key = (src, metric)
        ring = self.series.get(key)
        if ring is None:
            ring = self.series[key] = deque(maxlen=self._ring)
        ring.append((ts, value))

    def _on_disconnect(self, src: str, cid: int, graceful: bool) -> None:
        with self._lock:
            rec = self.sources.get(src)
            # a stale connection's EOF must not kill a reconnected source
            if rec is None or rec.get("conn_id") != cid:
                return
            was_alive = rec.get("alive", False)
            rec["alive"] = False
            rec["graceful"] = graceful or self._stop.is_set()
            meta = dict(rec.get("meta") or {})
        if was_alive and not graceful and not self._stop.is_set():
            # SIGKILL fast path: EOF -> death event in milliseconds
            self._dispatch_event(src, {"kind": "death", "ts": _now(),
                                       "detail": meta})

    def _reap_loop(self) -> None:
        """Wedged-not-dead backstop: a source that stops pushing without
        its socket dying is declared dead after telemetry_death_after_s."""
        while not self._stop.is_set():
            after = float(_flags.flag("telemetry_death_after_s"))
            self._stop.wait(max(0.05, after / 3.0))
            if self._stop.is_set():
                return
            now, dead = _now(), []
            with self._lock:
                for src, rec in self.sources.items():
                    if rec.get("alive") and \
                            now - rec.get("last_seen", now) > after:
                        rec["alive"] = False
                        dead.append((src, dict(rec.get("meta") or {})))
            for src, meta in dead:
                self._dispatch_event(src, {"kind": "death", "ts": _now(),
                                           "detail": meta})

    # -- events / incidents / subscribers --
    def subscribe(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)

    def _dispatch_event(self, src: str, ev: Dict[str, Any]) -> None:
        if not isinstance(ev, dict):
            return
        ev = dict(ev)
        ev["source"] = src
        ev.setdefault("ts", _now())
        with self._lock:
            self.events.append(ev)
            subs = list(self._subscribers)
        for fn in subs:
            try:
                fn(ev)
            except Exception:
                pass  # a bad subscriber must not break ingest
        detail = ev.get("detail") or {}
        if ev.get("kind") == "dump":
            iid = detail.get("incident_id")
            if iid:
                with self._lock:
                    inc = self.incidents.get(str(iid))
                    if inc is not None and detail.get("path"):
                        # the event rides the process-DEFAULT exporter's
                        # connection; the dump's own source wins
                        inc["dumps"].append(
                            {"source": detail.get("source") or src,
                             "path": detail["path"]})
            else:
                self._start_incident(src, str(detail.get("reason")
                                              or "incident"))

    def _start_incident(self, origin: str, reason: str) -> None:
        """Fan a correlated dump command to every live source (origin
        included — its incident dump carries the shared id, unlike the
        local one that started this). Rate-limited: a crash loop makes
        one fleet dump set per window, not a storm."""
        now = time.monotonic()
        with self._lock:
            min_s = float(_flags.flag("telemetry_incident_min_interval_s"))
            if now - self._last_incident < min_s:
                return
            self._last_incident = now
            iid = "inc-" + uuid.uuid4().hex[:12]
            targets = [s for s, r in self.sources.items() if r.get("alive")]
            self.incidents[iid] = {"id": iid, "ts": _now(),
                                   "origin": origin, "reason": reason,
                                   "targets": targets, "dumps": []}
            for s in targets:
                self._commands.setdefault(s, deque(maxlen=32)).append(
                    {"op": "dump", "incident_id": iid, "reason": reason})

    def _drain_commands(self, src: str) -> List[Dict[str, Any]]:
        with self._lock:
            q = self._commands.get(src)
            if not q:
                return []
            out = list(q)
            q.clear()
            return out

    # -- alert rules --
    def add_rule(self, name: str, metric: str, threshold: float,
                 kind: str = "gauge") -> None:
        """Threshold rule: fires (one 'alert' event per transition) when
        `metric` in a source's gauges/counters exceeds `threshold`."""
        with self._lock:
            self._rules.append({"name": name, "metric": metric,
                                "threshold": float(threshold),
                                "kind": kind})

    def _eval_rules(self, src: str) -> None:
        with self._lock:
            rec = self.sources.get(src) or {}
            gauges = dict(rec.get("gauges") or {})
            counters = dict(rec.get("counters") or {})
            rules = list(self._rules)
        fired: List[Tuple[str, Dict[str, Any]]] = []
        cleared: List[str] = []
        for rule in rules:
            vals = counters if rule["kind"] == "counter" else gauges
            v = vals.get(rule["metric"])
            self._transition(
                src, rule["name"], v is not None and v > rule["threshold"],
                {"metric": rule["metric"], "value": v,
                 "threshold": rule["threshold"]}, fired, cleared)
        # built-in multi-window burn rule (obs/slo.py publishes one
        # slo.burn.<w>s gauge per window): EVERY window above threshold
        # means a sustained budget burn, not a blip
        burns = {k: v for k, v in gauges.items()
                 if k.startswith("slo.burn.")}
        self._transition(
            src, "slo_burn",
            bool(burns) and min(burns.values()) > self.burn_threshold,
            {"burn": burns, "threshold": self.burn_threshold},
            fired, cleared)
        for name, detail in fired:
            self._dispatch_event(src, {"kind": "alert", "ts": _now(),
                                       "detail": dict(detail, rule=name)})

    def _transition(self, src, name, active, detail, fired, cleared):
        key = (src, name)
        with self._lock:
            was = key in self._active_alerts
            if active and not was:
                self._active_alerts[key] = {"source": src, "rule": name,
                                            "since": _now(), **detail}
                fired.append((name, detail))
            elif not active and was:
                del self._active_alerts[key]
                cleared.append(name)
            elif active:
                self._active_alerts[key].update(detail)

    def alerts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(a) for a in self._active_alerts.values()]

    # -- autoscaler integration --
    def pool_update(self, doc: Dict[str, Any]) -> None:
        """The co-located Autoscaler publishes its pool state here after
        every tick (target vs actual replicas, last decision + trigger,
        blocked verdict); `monitor top` renders it, and the built-in
        `scale_blocked` rule fires ONE alert event per transition into a
        scale-out that cannot be satisfied (spawn budget exhausted / HBM
        refused)."""
        with self._lock:
            self._pool = dict(doc)
        fired: List[Tuple[str, Dict[str, Any]]] = []
        cleared: List[str] = []
        self._transition(
            "autoscaler", "scale_blocked", bool(doc.get("blocked")),
            {"reason": doc.get("blocked_reason"),
             "target": doc.get("target"), "actual": doc.get("actual")},
            fired, cleared)
        for name, detail in fired:
            self._dispatch_event("autoscaler",
                                 {"kind": "alert", "ts": _now(),
                                  "detail": dict(detail, rule=name)})

    # -- read side --
    def mergeable_snapshots(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {src: {"counters": dict(rec.get("counters") or {}),
                          "gauges": dict(rec.get("gauges") or {}),
                          "histograms": dict(rec.get("histograms") or {})}
                    for src, rec in self.sources.items()}

    def merged(self) -> Dict[str, Any]:
        """True fleet-wide view: counters/gauges summed, histograms
        merged bin-wise (monitor.merge_snapshots)."""
        return _monitor.merge_snapshots(
            self.mergeable_snapshots().values())

    def scrape(self) -> str:
        """ONE Prometheus scrape for the whole fleet: every source's
        series under `source=` labels + merged-sketch `_q` quantile
        families (monitor.prometheus_text_multi)."""
        return _monitor.prometheus_text_multi(self.mergeable_snapshots())

    def _rate(self, src: str, metric: str) -> float:
        # caller holds self._lock
        ring = self.series.get((src, metric))
        if not ring or len(ring) < 2:
            return 0.0
        (t0, v0), (t1, v1) = ring[0], ring[-1]
        return (v1 - v0) / (t1 - t0) if t1 > t0 else 0.0

    def fleet_table(self) -> List[Dict[str, Any]]:
        from . import merge as _merge
        from . import slo as _slo
        rows: List[Dict[str, Any]] = []
        with self._lock:
            items = [(s, dict(r)) for s, r in sorted(self.sources.items())]
            rates = {s: self._rate(s, "serving.e2e_latency.count")
                     for s, _ in items}
        p99s: Dict[str, float] = {}
        for src, rec in items:
            gauges = rec.get("gauges") or {}
            hist = (rec.get("histograms") or {}).get("serving.e2e_latency")
            p99 = 0.0
            if isinstance(hist, dict) and hist.get("count"):
                p99 = _monitor.Histogram.from_payload(
                    "serving.e2e_latency", hist).quantile(0.99)
            hbm = max([v for k, v in gauges.items()
                       if k.startswith("mem.") and k.endswith("bytes")]
                      or [0])
            p99s[src] = p99
            rows.append({"source": src, "role": rec.get("role"),
                         "alive": bool(rec.get("alive")),
                         "qps": rates.get(src, 0.0),
                         "queue": gauges.get("serving.queue_depth", 0),
                         "p99_s": p99,
                         "burn": _slo.burn_from_gauges(gauges),
                         "hbm_bytes": hbm})
        worst, _, _, skew = _merge.skew_over_median(
            {s: v for s, v in p99s.items() if v > 0})
        for row in rows:
            row["straggler"] = (row["source"] == worst and skew >= 1.5)
        return rows

    def snapshot_doc(self) -> Dict[str, Any]:
        """The `monitor top` document (served over the query verb)."""
        rows = self.fleet_table()
        with self._lock:
            events = list(self.events)[-16:]
            incidents = [dict(i) for i in self.incidents.values()]
            pool = dict(self._pool)
        return {"fleet": self.fleet, "ts": _now(), "sources": rows,
                "events": events, "incidents": incidents,
                "alerts": self.alerts(), "pool": pool}


# ---------------------------------------------------------------------------
# CLI helpers (python -m paddle_tpu.monitor top)
# ---------------------------------------------------------------------------

def query_collector(host: str, port: int,
                    timeout_s: float = _IO_TIMEOUT_S) -> Dict[str, Any]:
    """One query round-trip: 'PDTM' {"op": "query"} -> the collector's
    snapshot_doc in the 'PDTA' body."""
    sock = _net.dial((host, int(port)), timeout=timeout_s,
                     plane="telemetry")
    try:
        _net.send_crc_frame(sock, _net.PDTM_MAGIC,
                            json.dumps({"op": "query"}).encode())
        ack = json.loads(_net.recv_crc_frame(
            sock, _net.PDTA_MAGIC,
            deadline=time.monotonic() + timeout_s))
    finally:
        sock.close()
    return ack.get("doc") or {}


def render_top(doc: Dict[str, Any]) -> str:
    """The live fleet table: one row per source (qps / queue / p99 / burn
    / HBM / role), stragglers starred, recent events and open incidents
    below."""
    rows = doc.get("sources") or []
    lines = ["-" * 78,
             f"fleet '{doc.get('fleet', '?')}' — {len(rows)} sources, "
             f"{sum(1 for r in rows if r.get('alive'))} alive",
             "-" * 78,
             f"{'Source':<18}{'Role':<10}{'QPS':>8}{'Queue':>7}"
             f"{'p99(ms)':>9}{'Burn':>7}{'HBM(MB)':>9}  State"]
    for r in rows:
        state = "up" if r.get("alive") else "DOWN"
        if r.get("straggler"):
            state += " *straggler*"
        lines.append(
            f"{str(r.get('source'))[:17]:<18}"
            f"{str(r.get('role') or '-')[:9]:<10}"
            f"{r.get('qps', 0.0):>8.1f}{r.get('queue', 0):>7}"
            f"{r.get('p99_s', 0.0) * 1e3:>9.2f}"
            f"{r.get('burn', 0.0):>7.2f}"
            f"{r.get('hbm_bytes', 0) / 1e6:>9.1f}  {state}")
    pool = doc.get("pool") or {}
    if pool:
        line = (f"pool: target={pool.get('target')} "
                f"actual={pool.get('actual')}")
        if pool.get("blocked"):
            line += f"  [BLOCKED: {pool.get('blocked_reason') or '?'}]"
        last = pool.get("last") or {}
        if last:
            delta = last.get("delta") or 0
            line += (f"  last={last.get('action')}"
                     f"{delta:+d} trigger={last.get('reason')}"
                     f" outcome={last.get('outcome')}")
        lines.append(line)
    alerts = doc.get("alerts") or []
    for a in alerts:
        lines.append(f"ALERT {a.get('rule')} on {a.get('source')}: "
                     + ", ".join(f"{k}={v}" for k, v in sorted(a.items())
                                 if k not in ("rule", "source", "since")))
    evs = doc.get("events") or []
    if evs:
        lines.append(f"recent events ({len(evs)}):")
        for ev in evs[-8:]:
            lines.append(f"  {ev.get('kind')} source={ev.get('source')} "
                         f"{ev.get('detail') or {}}")
    for inc in doc.get("incidents") or []:
        lines.append(f"incident {inc.get('id')} reason={inc.get('reason')} "
                     f"origin={inc.get('origin')} "
                     f"dumps={len(inc.get('dumps') or [])}/"
                     f"{len(inc.get('targets') or [])}")
    lines.append("-" * 78)
    return "\n".join(lines)
