"""XLA cost-analysis hook — attributed FLOPs/bytes per compiled executable.

`bench.py`'s MFU was one opaque number derived from a hand-written FLOP
formula; XLA already knows the real count. `compiled.cost_analysis()`
exposes the compiler's own per-executable estimate (flops, bytes accessed),
so MFU can be *attributed* — the executable's true FLOPs over the measured
step time — and the roofline gap split per phase by the step timeline.
`TrainStep.cost_analysis()` / `SPMDTrainStep.cost_analysis()` wrap this for
the training step executable.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

__all__ = ["executable_cost", "attributed_mfu", "roofline_gap"]


def executable_cost(compiled) -> Dict[str, float]:
    """Normalized {flops, bytes_accessed, ...} from an AOT-compiled
    executable's cost_analysis(). jax returns a dict or a one-element list
    of dicts depending on version; keys are XLA's ('flops',
    'bytes accessed', 'utilization0{}', ...). Absent/failed analysis
    (some backends) -> {}."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out: Dict[str, float] = {}
    for key, norm in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("transcendentals", "transcendentals")):
        if key in ca and isinstance(ca[key], (int, float)):
            out[norm] = float(ca[key])
    return out


def attributed_mfu(flops_per_step: float, step_time_s: float,
                   peak_flops: float) -> float:
    """MFU from the compiler-attributed FLOP count: what fraction of the
    chip's peak the executable actually sustained."""
    if step_time_s <= 0 or peak_flops <= 0:
        return 0.0
    return flops_per_step / (step_time_s * peak_flops)


def roofline_gap(cost: Dict[str, float], step_time_s: float,
                 peak_flops: float,
                 hbm_bytes_per_s: Optional[float] = None) -> Dict[str, Any]:
    """Which wall is the step leaning on: compute (MFU) vs memory
    (HBM-roofline fraction), both from the SAME attributed cost dict."""
    out: Dict[str, Any] = {}
    if "flops" in cost:
        out["mfu"] = attributed_mfu(cost["flops"], step_time_s, peak_flops)
    if hbm_bytes_per_s and "bytes_accessed" in cost and step_time_s > 0:
        out["hbm_frac"] = cost["bytes_accessed"] / (step_time_s *
                                                    hbm_bytes_per_s)
    if "mfu" in out and "hbm_frac" in out:
        out["bound"] = "memory" if out["hbm_frac"] > out["mfu"] else "compute"
    return out
