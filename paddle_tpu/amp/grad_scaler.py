"""Dynamic-loss-scaling GradScaler.

Reference parity: `python/paddle/amp/grad_scaler.py:26` wrapping AmpScaler
(`fluid/dygraph/amp/loss_scaler.py`): scale loss, unscale grads, skip step on
non-finite grads, grow/shrink the scale. The reference fuses the finiteness
scan into one kernel (`operators/amp/check_finite_and_unscale_op.cu`); here
the fusion goes further (FLAGS_amp_fused_update, default on): `step()` hands
the optimizer a device `inv_scale` scalar and the unscale, the finite-scan,
the found_inf GATE and the parameter update all run inside the optimizer's
single donated executable — no host sync sits between backward and the
update dispatch. The found_inf flag is read (one host sync) only afterwards,
in `update()`, where the scale grow/shrink decision needs it; by then it
overlaps the device work instead of serializing it.

The scale itself lives as a CACHED DEVICE SCALAR (re-uploaded only when the
scale changes, i.e. every `incr_every_n_steps` good steps or on overflow) and
enters `scale(loss)` as an array argument — never a fresh Python float burned
into the traced multiply, which would force a recompile at every scale
change.

Per-optimizer state (reference OptimizerState, grad_scaler.py:192-207)
guarantees grads are unscaled exactly once even in the
`scaler.unscale_(opt) -> clip -> scaler.step(opt)` pattern — that explicit
pattern keeps its legacy semantics (host-synced found_inf before step).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import monitor as _monitor
from ..core import flags as _flags
from ..core.tensor import Tensor
from ..ops._dispatch import ensure_tensor, run_op


@jax.jit
def _fused_unscale(grads, inv):
    """Scale every grad by inv and AND-reduce finiteness in one XLA program."""
    scaled = [g * inv.astype(g.dtype) for g in grads]
    finite = jnp.asarray(True)
    for g in scaled:
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
    return scaled, jnp.logical_not(finite)


def _scale_mul(a, s):
    # s enters as an ARRAY argument: the jitted multiply is shape-keyed, so
    # a scale change re-uses the same executable (no constant burn-in)
    return a * s.astype(a.dtype)


class GradScaler:
    # per-optimizer lifecycle (reference OptimizerState)
    _READY, _UNSCALED, _STEPPED = 0, 1, 2

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio, self._decr_ratio = incr_ratio, decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._opt_states: dict = {}
        # device-scalar cache for the scale (H2D only on change) + the
        # deferred found_inf flags of fused (gated) optimizer steps
        self._scale_cached = None
        self._scale_arr = None
        self._inv_scale_arr = None
        self._pending_found: list = []

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def _dev_scales(self):
        if self._scale_cached != self._scale or self._scale_arr is None:
            self._scale_cached = self._scale
            self._scale_arr = jnp.asarray(self._scale, jnp.float32)
            self._inv_scale_arr = jnp.asarray(1.0 / self._scale, jnp.float32)
        return self._scale_arr, self._inv_scale_arr

    def scale(self, loss):
        if not self._enable:
            return loss
        s, _ = self._dev_scales()
        return run_op(_scale_mul, [ensure_tensor(loss), Tensor(s)],
                      "amp_scale")

    def unscale_(self, optimizer):
        if not self._enable:
            return
        state = self._opt_states.get(id(optimizer), self._READY)
        if state == self._UNSCALED:
            raise RuntimeError("unscale_() has already been called on this "
                               "optimizer since the last update().")
        if state == self._STEPPED:
            raise RuntimeError("unscale_() is being called after step().")
        params = [p for p in (optimizer._parameter_list or [])
                  if p.grad is not None]
        if params:
            grads = [p.grad._value if isinstance(p.grad, Tensor) else p.grad
                     for p in params]
            _, inv = self._dev_scales()
            scaled, found = _fused_unscale(grads, inv)
            self._found_inf = bool(found) or self._found_inf  # one host sync
            for p, g in zip(params, scaled):
                p.grad = g
        self._opt_states[id(optimizer)] = self._UNSCALED

    def _can_fuse(self, optimizer) -> bool:
        """Fused path: unscale+gate inside the optimizer's donated
        executable. Needs the flag, a fused-capable optimizer, and no
        SelectedRows grads (the sparse rule runs eagerly)."""
        if not _flags.flag("amp_fused_update"):
            return False
        if not hasattr(optimizer, "_fused_cache"):
            return False
        from ..core.selected_rows import SelectedRows
        return not any(isinstance(p.grad, SelectedRows)
                       for p in (optimizer._parameter_list or [])
                       if p.grad is not None)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        state = self._opt_states.get(id(optimizer), self._READY)
        if state == self._STEPPED:
            raise RuntimeError("step() has already been called on this "
                               "optimizer since the last update().")
        if state != self._UNSCALED and self._can_fuse(optimizer):
            # fused: ONE dispatch does unscale + finite-scan + gate +
            # update; found_inf comes back as a device flag whose host
            # read is deferred to update()
            _, inv = self._dev_scales()
            found = optimizer.step(inv_scale=inv)
            if found is not None:
                self._pending_found.append((optimizer, found))
        else:
            if state != self._UNSCALED:
                self.unscale_(optimizer)
            if not self._found_inf:
                optimizer.step()
            elif _monitor._ENABLED:
                _monitor.count("amp.skipped_steps")
        self._opt_states[id(optimizer)] = self._STEPPED
        # Auto-advance the scale only once every optimizer seen this round
        # has stepped — a second optimizer still in UNSCALED state must keep
        # its marker (and the shared found_inf) until its own step().
        if all(v == self._STEPPED for v in self._opt_states.values()):
            self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def _resolve_found(self):
        """Read deferred fused found_inf flags (the one host sync of the
        fused path — it lands AFTER the update dispatch) and commit each
        optimizer's step count accordingly."""
        for opt, _found in self._pending_found:
            found = opt._resolve_pending()
            if found:
                self._found_inf = True
                if _monitor._ENABLED:
                    _monitor.count("amp.skipped_steps")
        self._pending_found = []

    def update(self):
        self._resolve_found()
        self._opt_states.clear()
        if not (self._enable and self._dynamic):
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
                if _monitor._ENABLED:
                    _monitor.count("amp.scale_updates")
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
                if _monitor._ENABLED:
                    _monitor.count("amp.scale_updates")
        self._found_inf = False

    def state_dict(self):
        """Round-trips the FULL dynamic-scaling state: the scale, both
        streak counters AND the pending found_inf of an unscale_ whose
        step()/update() had not landed yet — so a guard checkpoint cut
        between unscale_ and step resumes with the identical
        grow/shrink trajectory."""
        self._resolve_found()
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "incr_count": self._good_steps,
                "decr_count": self._bad_steps,
                "found_inf": bool(self._found_inf)}

    def load_state_dict(self, state_dict):
        self._scale = state_dict.get("scale", self._scale)
        self._good_steps = state_dict.get("incr_count", 0)
        self._bad_steps = state_dict.get("decr_count", 0)
        self._found_inf = bool(state_dict.get("found_inf", False))
        self._pending_found = []
