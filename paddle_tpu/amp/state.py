"""AMP autocast state, consumed by matmul/conv/linear dispatch.

Reference parity: `imperative/amp_auto_cast.cc` (tracer-hooked input casting
with white/black lists). TPU-first: bf16 is the default low precision (MXU
native, no loss scaling needed); fp16 supported for parity.
"""
from __future__ import annotations

import threading

import jax.numpy as jnp

from ..core.dtype import convert_dtype

# ops cast to low precision (white list — matmul-class, reference
# fluid/contrib/mixed_precision/fp16_lists.py white_list)
WHITE_LIST = {"matmul", "conv2d", "linear", "einsum", "bmm", "mm", "attention"}
# ops kept in fp32 (black list: softmax_with_cross_entropy, norms, exp, …)
BLACK_LIST = {"cross_entropy", "softmax", "log_softmax", "layer_norm", "batch_norm",
              "mean", "sum", "exp", "log"}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"


_STATE = _AmpState()


def amp_state():
    return _STATE


def amp_enabled() -> bool:
    return _STATE.enabled


def maybe_cast(*arrays):
    """Cast floating arrays to the autocast dtype when AMP is active (white-list op)."""
    if not _STATE.enabled:
        return arrays if len(arrays) > 1 else arrays[0]
    out = tuple(a.astype(_STATE.dtype)
                if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
                and a.dtype != _STATE.dtype else a
                for a in arrays)
    return out if len(out) > 1 else out[0]


class auto_cast:
    """paddle.amp.auto_cast parity (context manager / decorator)."""

    def __init__(self, enable=True, custom_white_list=None, custom_black_list=None,
                 level="O1", dtype="bfloat16"):
        self.enable = enable
        self.level = level
        self.dtype = convert_dtype(dtype)

    def __enter__(self):
        self._prev = (_STATE.enabled, _STATE.dtype, _STATE.level)
        _STATE.enabled = self.enable
        _STATE.dtype = self.dtype
        _STATE.level = self.level
        return self

    def __exit__(self, *exc):
        _STATE.enabled, _STATE.dtype, _STATE.level = self._prev
        return False

    def __call__(self, fn):
        def wrapper(*a, **kw):
            with auto_cast(self.enable, level=self.level, dtype=str(self.dtype)):
                return fn(*a, **kw)
        return wrapper


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model parameters to low precision (paddle.amp.decorate)."""
    dt = convert_dtype(dtype)
    items = models if isinstance(models, (list, tuple)) else [models]
    for m in items:
        if m is not None:
            m.to(dtype=dt)
    if optimizers is None:
        return models if len(items) > 1 else items[0]
    return (models, optimizers)
