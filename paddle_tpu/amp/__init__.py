"""paddle.amp parity namespace (bf16-first on TPU)."""
from .state import auto_cast, decorate, amp_enabled, amp_state, maybe_cast  # noqa: F401
from .grad_scaler import GradScaler  # noqa: F401

amp_guard = auto_cast  # fluid alias
