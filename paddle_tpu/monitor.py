"""paddle_tpu.monitor — the framework-wide telemetry plane.

Reference parity: `paddle/fluid/platform/monitor.h` (the STAT_INT registry,
STAT_ADD/STAT_RESET macros over `platform::StatRegistry`) plus the span side
of `platform/profiler/event_tracing.h` (RecordEvent ranges). One process-wide
registry of counters, gauges and histograms that every layer reports into:

  - op dispatch (`ops/_dispatch.run_op`): per-op counts + duration histograms
  - autograd (`core/autograd.backward`): walk timing, nodes walked, fused hits
  - JIT (`jit/train_step.py`, `jit/to_static.py`): trace/RETRACE counts with
    the argument signatures that caused each retrace — the single most
    important TPU perf signal (a retrace = a full XLA recompile)
  - collectives (`parallel/collective.py`): per-collective counts + bytes
  - fleet executor (`distributed/fleet_executor.py`): message counts,
    inbox-depth gauges
  - data loading (`io/dataloader.py`): queue-wait + batch-build histograms
  - optimizer (`optimizer/optimizer.py`): step counts + durations
  - training guard (`guard/supervisor.py`): `guard.steps`/`guard.bad_steps`/
    `guard.rollbacks`/`guard.snapshots`/`guard.checkpoints`/`guard.stalls`/
    `guard.step_errors`/`guard.preempts`/`guard.resumes`/
    `guard.desync_checks`/`guard.desync_errors` counters — every recovery
    the supervisor performs is visible next to the fault that provoked it;
    `amp.skipped_steps`/`amp.scale_updates` from the GradScaler
  - lazy eager executor (`ops/lazy.py`, behind `FLAGS_lazy_eager`):
    `lazy.ops_deferred` (ops captured into the per-thread segment) /
    `lazy.flushes` (segments materialized) / `lazy.dispatches` (jitted
    replay calls — the number that replaces per-op dispatch count) /
    `lazy.ops_flushed` / `lazy.cache_hits` (segment executable reused) /
    `lazy.fallback_ops` (ops that bypassed deferral); segment compiles
    land in the retrace plane as `jit.lazy_segment.traces`/`.retraces`
  - static analysis (`analysis/` tpu-lint, behind `FLAGS_lint`):
    `lint.findings` (trace hazards found at trace time) / `lint.files`
    (distinct source files linted) — a nonzero findings counter in a
    training job is a retrace storm or host sync waiting to happen
  - serving (`serving/engine.py`): `serving.queue_depth` gauge,
    `serving.queue_wait`/`serving.e2e_latency`/`serving.batch_size`
    histograms, `serving.padding_waste_elems`/`serving.padded_rows`,
    `serving.rejected`/`serving.deadline_expired`/`serving.compiles`
    counters — one Prometheus scrape covers the whole serving path

Everything is gated by `FLAGS_monitor` (off by default): instrumented call
sites check the module attribute `_ENABLED` — one attribute load on the
disabled path, no hook installation, no allocation. `core.flags.watch_flag`
keeps `_ENABLED` in sync with `paddle.set_flags({"FLAGS_monitor": ...})`.

Outputs: `snapshot()` (nested dict), `report()` (rendered table, the
`Profiler.summary()` sibling), `export_json(path)`, `prometheus_text()` /
`export_prometheus(path)`, and `span(name)` trace ranges that ALSO feed any
active `paddle_tpu.profiler.Profiler`'s host-event stream so one chrome
trace carries both planes (`Profiler.export` embeds `snapshot()` as trace
metadata).
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .core import flags as _flags

__all__ = [
    "Counter", "Gauge", "Histogram", "StatRegistry",
    "enabled", "enable", "disable",
    "counter", "gauge", "histogram",
    "count", "gauge_set", "observe", "log_event", "record_op",
    "record_collective", "record_retrace", "record_span",
    "span", "snapshot", "report", "reset",
    "mergeable_snapshot", "merge_snapshots",
    "export_json", "prometheus_text", "prometheus_text_multi",
    "export_prometheus",
]

# Hot-path gate: instrumented sites read this module attribute directly.
_ENABLED: bool = bool(_flags.flag("monitor"))


def _on_flag(value) -> None:
    global _ENABLED
    _ENABLED = bool(value)


_flags.watch_flag("monitor", _on_flag)


def enabled() -> bool:
    return _ENABLED


def enable() -> None:
    _flags.set_flags({"monitor": True})


def disable() -> None:
    _flags.set_flags({"monitor": False})


# ---- metric primitives (monitor.h StatValue role) -------------------------

class Counter:
    """Monotonic int/float accumulator (STAT_ADD)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def add(self, delta=1) -> None:
        with self._lock:
            self.value += delta

    def get(self):
        return self.value

    def reset(self) -> None:
        with self._lock:
            self.value = 0


class Gauge:
    """Last-write-wins instantaneous value (queue depth, cache size)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def set(self, value) -> None:
        with self._lock:
            self.value = value

    def add(self, delta=1) -> None:
        with self._lock:
            self.value += delta

    def get(self):
        return self.value

    def reset(self) -> None:
        with self._lock:
            self.value = 0


# Default buckets suit durations in seconds: 1us .. 10s, exponential.
_DEFAULT_BUCKETS: Tuple[float, ...] = (
    1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0)

# DDSketch-style quantile sketch parameters: log buckets of ratio gamma
# guarantee |estimate - true| <= alpha * true for every quantile — the 8
# fixed exponential buckets above are fine for a Prometheus scrape but
# cannot produce the accurate p99 SLO routing needs. alpha=0.005 -> <=1%
# relative error with two sketch buckets to spare.
_SKETCH_ALPHA = 0.005
_SKETCH_GAMMA = (1.0 + _SKETCH_ALPHA) / (1.0 - _SKETCH_ALPHA)
_SKETCH_LOG_GAMMA = math.log(_SKETCH_GAMMA)
# ~2048 bins cover >10 orders of magnitude at 1% error; beyond that the
# LOWEST bins collapse together (the tail quantiles everyone routes on
# live in the highest bins, which never lose precision)
_SKETCH_MAX_BINS = 2048


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each bucket counts
    observations <= its upper bound; +Inf is implicit via `count`) plus a
    bounded-relative-error log-bucket quantile sketch (DDSketch-style):
    `quantile(q)` is within `_SKETCH_ALPHA` relative error of the exact
    value, at O(bins) memory independent of observation count."""

    __slots__ = ("name", "buckets", "bucket_counts", "count", "sum",
                 "min", "max", "_sketch", "_sketch_zero", "_lock")

    def __init__(self, name: str, buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.buckets = tuple(sorted(buckets or _DEFAULT_BUCKETS))
        self.bucket_counts = [0] * len(self.buckets)
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        self._sketch: Dict[int, int] = {}   # log-bin index -> count
        self._sketch_zero = 0               # observations <= 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self.count += 1
            self.sum += value
            if value < self.min:
                self.min = value
            if value > self.max:
                self.max = value
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    self.bucket_counts[i] += 1
            if value > 0.0:
                idx = math.ceil(math.log(value) / _SKETCH_LOG_GAMMA)
                self._sketch[idx] = self._sketch.get(idx, 0) + 1
                if len(self._sketch) > _SKETCH_MAX_BINS:
                    self._collapse_locked()
            else:
                self._sketch_zero += 1

    def _collapse_locked(self) -> None:
        # fold the two lowest bins together (DDSketch collapse rule):
        # precision degrades only at the extreme LOW tail
        lo = sorted(self._sketch)
        a, b = lo[0], lo[1]
        self._sketch[b] += self._sketch.pop(a)

    def quantile(self, q: float) -> float:
        """Sketch quantile estimate: <= _SKETCH_ALPHA relative error.
        q in [0, 1]; returns 0.0 on an empty histogram."""
        with self._lock:
            return self._quantile_locked(q)

    def _quantile_locked(self, q: float) -> float:
        total = self._sketch_zero + sum(self._sketch.values())
        if total == 0:
            return 0.0
        rank = q * (total - 1)
        seen = self._sketch_zero
        if rank < seen:
            return 0.0
        for idx in sorted(self._sketch):
            seen += self._sketch[idx]
            if rank < seen:
                # midpoint of (gamma^(i-1), gamma^i] in relative terms
                return 2.0 * _SKETCH_GAMMA ** idx / (_SKETCH_GAMMA + 1.0)
        return self.max

    def quantiles(self, qs=(0.5, 0.95, 0.99)) -> Dict[float, float]:
        with self._lock:
            return {q: self._quantile_locked(q) for q in qs}

    # -- mergeable form (the fleet telemetry plane ships these) --

    def sketch_payload(self) -> Dict[str, Any]:
        """JSON-able mergeable form: the raw log-bins plus the running
        aggregates. `merge()` on the receiving side reconstructs EXACT
        fleet-wide quantiles (bin-wise sums preserve the <=1% bound —
        averaging per-source p99s would not)."""
        with self._lock:
            return {
                "bins": {str(i): c for i, c in self._sketch.items()},
                "zero": self._sketch_zero,
                "count": self.count,
                "sum": self.sum,
                "min": self.min if self.count else None,
                "max": self.max,
                "buckets": list(self.buckets),
                "bucket_counts": list(self.bucket_counts),
            }

    @classmethod
    def from_payload(cls, name: str,
                     payload: Dict[str, Any]) -> "Histogram":
        h = cls(name, buckets=tuple(payload.get("buckets")
                                    or _DEFAULT_BUCKETS))
        h.merge(payload)
        return h

    def merge(self, other) -> "Histogram":
        """Fold another histogram (or a `sketch_payload()` dict) into this
        one. Sketches merge exactly: per-bin counts add, so the merged
        quantiles carry the same <=1% relative-error bound as a single
        sketch fed the pooled observations. Returns self."""
        if isinstance(other, Histogram):
            other = other.sketch_payload()
        cnt = int(other.get("count", 0))
        obuckets = tuple(other.get("buckets") or ())
        ocounts = list(other.get("bucket_counts") or ())
        with self._lock:
            if cnt:
                self.count += cnt
                self.sum += float(other.get("sum", 0.0))
                omin = other.get("min")
                if omin is not None and float(omin) < self.min:
                    self.min = float(omin)
                omax = float(other.get("max", 0.0))
                if omax > self.max:
                    self.max = omax
            if obuckets == self.buckets and len(ocounts) == len(self.buckets):
                for i, c in enumerate(ocounts):
                    self.bucket_counts[i] += int(c)
            elif ocounts:
                # boundary mismatch: re-bucket the other side's per-bucket
                # deltas at their upper bounds (cumulative stays monotone;
                # the sketch below keeps the accurate quantiles)
                prev = 0
                for ub, c in zip(obuckets, ocounts):
                    delta = int(c) - prev
                    prev = int(c)
                    if delta <= 0:
                        continue
                    for i, mine in enumerate(self.buckets):
                        if ub <= mine:
                            self.bucket_counts[i] += delta
            self._sketch_zero += int(other.get("zero", 0))
            for idx, c in (other.get("bins") or {}).items():
                i = int(idx)
                self._sketch[i] = self._sketch.get(i, 0) + int(c)
            while len(self._sketch) > _SKETCH_MAX_BINS:
                self._collapse_locked()
        return self

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            cnt = self.count
            return {
                "count": cnt,
                "sum": self.sum,
                "avg": (self.sum / cnt) if cnt else 0.0,
                "min": self.min if cnt else 0.0,
                "max": self.max,
                "buckets": dict(zip(self.buckets, self.bucket_counts)),
                "p50": self._quantile_locked(0.5),
                "p95": self._quantile_locked(0.95),
                "p99": self._quantile_locked(0.99),
            }

    def reset(self) -> None:
        with self._lock:
            self.bucket_counts = [0] * len(self.buckets)
            self.count = 0
            self.sum = 0.0
            self.min = float("inf")
            self.max = 0.0
            self._sketch = {}
            self._sketch_zero = 0


# ---- registry (monitor.h StatRegistry role) --------------------------------

_EVENT_RING_CAP = 256


class StatRegistry:
    """Thread-safe get-or-create store of named metrics + an event ring
    (bounded structured log — retrace causes, anomalies)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._events: List[Dict[str, Any]] = []

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str,
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name,
                                                Histogram(name, buckets))
        return h

    def log_event(self, name: str, **payload) -> None:
        ev = {"ts": time.time(), "event": name}
        ev.update(payload)
        with self._lock:
            self._events.append(ev)
            if len(self._events) > _EVENT_RING_CAP:
                del self._events[: len(self._events) - _EVENT_RING_CAP]

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counters = {n: c.get() for n, c in self._counters.items()}
            gauges = {n: g.get() for n, g in self._gauges.items()}
            hists = list(self._histograms.items())
            events = list(self._events)
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {n: h.stats() for n, h in hists},
            "events": events,
        }

    def mergeable_snapshot(self) -> Dict[str, Any]:
        """Like snapshot(), but histograms come as `sketch_payload()` dicts
        so the receiving side can `merge_snapshots()` them into true
        fleet-wide quantiles (a stats() dict cannot be merged — its
        quantiles are already collapsed)."""
        with self._lock:
            counters = {n: c.get() for n, c in self._counters.items()}
            gauges = {n: g.get() for n, g in self._gauges.items()}
            hists = list(self._histograms.items())
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {n: h.sketch_payload() for n, h in hists},
        }

    def reset(self) -> None:
        """Drop every metric (STAT_RESET role): a fresh snapshot after
        reset carries no stale zero-valued names. Holders of metric objects
        obtained before the reset keep functioning but are detached."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._events.clear()


_REGISTRY = StatRegistry()


def registry() -> StatRegistry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str, buckets=None) -> Histogram:
    return _REGISTRY.histogram(name, buckets)


# ---- instrumentation entry points (the STAT_ADD call sites use these) ------

def count(name: str, delta=1) -> None:
    _REGISTRY.counter(name).add(delta)


def gauge_set(name: str, value) -> None:
    _REGISTRY.gauge(name).set(value)


def observe(name: str, value: float) -> None:
    _REGISTRY.histogram(name).observe(value)


def log_event(name: str, **payload) -> None:
    _REGISTRY.log_event(name, **payload)


def record_op(name: str, dur: float) -> None:
    """One eager op dispatched through `ops._dispatch.run_op`."""
    _REGISTRY.counter("dispatch.op_count").add(1)
    _REGISTRY.counter(f"dispatch.op.{name}").add(1)
    _REGISTRY.histogram(f"dispatch.dur.{name}").observe(dur)


def record_collective(name: str, nbytes: int) -> None:
    """One collective API call moving (logically) `nbytes`."""
    _REGISTRY.counter("collective.count").add(1)
    _REGISTRY.counter("collective.bytes").add(nbytes)
    _REGISTRY.counter(f"collective.{name}.count").add(1)
    _REGISTRY.counter(f"collective.{name}.bytes").add(nbytes)


def record_retrace(kind: str, signature, first: bool) -> None:
    """A JIT cache event. first=True is the initial trace (expected, one
    compile); first=False is a RETRACE — a novel argument shape/dtype
    signature forced a full recompile. The signature is logged so the
    offending input can be padded/bucketed away."""
    if first:
        _REGISTRY.counter(f"jit.{kind}.traces").add(1)
    else:
        _REGISTRY.counter(f"jit.{kind}.retraces").add(1)
        _REGISTRY.counter("jit.retraces").add(1)
        _REGISTRY.log_event("jit.retrace", kind=kind,
                            signature=list(signature))


def arg_signature(arrays) -> Tuple[str, ...]:
    """Hashable (shape, dtype) signature of a flat array/tensor list."""
    sig = []
    for a in arrays:
        v = getattr(a, "_value", a)
        sig.append(f"{tuple(getattr(v, 'shape', ()))}:"
                   f"{getattr(v, 'dtype', type(v).__name__)}")
    return tuple(sig)


# ---- trace spans (event_tracing.h RecordEvent role) ------------------------

class _NullSpan:
    """Shared no-op context: the disabled span() path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def record_span(name: str, t0: float, t1: float, kind: str = "span") -> None:
    """Book one completed range: `span.<name>.count`/`.dur` metrics plus
    every active Profiler's host-event stream (and thereby the chrome
    trace). `monitor.span()` and the request-trace spans (obs/trace.py)
    both land here, so one dispatcher feeds both export planes."""
    _REGISTRY.counter(f"span.{name}.count").add(1)
    _REGISTRY.histogram(f"span.{name}.dur").observe(t1 - t0)
    from . import profiler as _profiler
    for p in tuple(_profiler._ACTIVE_STACK):
        p._record_op(name, t0, t1, kind)


class _Span:
    __slots__ = ("name", "kind", "_t0")

    def __init__(self, name: str, kind: str):
        self.name = name
        self.kind = kind
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        record_span(self.name, self._t0, time.time(), self.kind)
        return False


def span(name: str, kind: str = "span"):
    """Instrumentation range: `with monitor.span("stage"): ...`. Duration
    lands in `span.<name>.dur`; when a Profiler is active the range also
    appears on its host timeline. Disabled -> shared no-op context."""
    if not _ENABLED:
        return _NULL_SPAN
    return _Span(name, kind)


# ---- snapshots / reports / exporters ---------------------------------------

def snapshot() -> Dict[str, Any]:
    """Nested dict of every metric: {counters, gauges, histograms, events}."""
    return _REGISTRY.snapshot()


def mergeable_snapshot() -> Dict[str, Any]:
    """snapshot() with histograms in `Histogram.sketch_payload()` form —
    the shape `merge_snapshots()` consumes and the telemetry exporter
    ships over the wire."""
    return _REGISTRY.mergeable_snapshot()


def merge_snapshots(snaps) -> Dict[str, Any]:
    """Fold mergeable snapshots (see `mergeable_snapshot()`) from several
    sources into one fleet-wide view: counters and gauges SUM (a fleet
    queue depth is the sum of per-replica depths), histograms merge
    bin-wise into `Histogram` objects whose quantiles keep the sketch's
    <=1% relative-error bound — the one aggregation averaging per-source
    p99s can never give you."""
    counters: Dict[str, Any] = {}
    gauges: Dict[str, Any] = {}
    hists: Dict[str, Histogram] = {}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for name, v in (snap.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + v
        for name, v in (snap.get("gauges") or {}).items():
            gauges[name] = gauges.get(name, 0) + v
        for name, payload in (snap.get("histograms") or {}).items():
            if not isinstance(payload, dict) or "bins" not in payload:
                continue   # stats()-shaped entry: not mergeable, skip
            h = hists.get(name)
            if h is None:
                hists[name] = Histogram.from_payload(name, payload)
            else:
                h.merge(payload)
    return {"counters": counters, "gauges": gauges, "histograms": hists}


def events() -> List[Dict[str, Any]]:
    return _REGISTRY.events()


def reset() -> None:
    _REGISTRY.reset()


def report(time_unit: str = "ms") -> str:
    """Rendered stats table (Profiler.summary() sibling for the stats plane)."""
    return render_snapshot(
        _REGISTRY.snapshot(), time_unit=time_unit,
        title_right=f"(FLAGS_monitor={'1' if _ENABLED else '0'})")


def render_snapshot(snap: Dict[str, Any], time_unit: str = "ms",
                    title_right: str = "") -> str:
    """Render ANY snapshot()-shaped dict (live registry, or a JSON artifact
    loaded back by the `python -m paddle_tpu.monitor show` CLI)."""
    scale = {"s": 1.0, "ms": 1e3, "us": 1e6}.get(time_unit, 1e3)
    width = 78
    lines = ["-" * width,
             f"{'paddle_tpu.monitor':<58}{title_right:>20}",
             "-" * width]
    if snap.get("counters"):
        lines.append(f"{'Counter':<52}{'Value':>24}")
        for name in sorted(snap["counters"]):
            lines.append(f"{name[:51]:<52}{snap['counters'][name]:>24}")
        lines.append("-" * width)
    if snap.get("gauges"):
        lines.append(f"{'Gauge':<52}{'Value':>24}")
        for name in sorted(snap["gauges"]):
            lines.append(f"{name[:51]:<52}{snap['gauges'][name]:>24}")
        lines.append("-" * width)
    if snap.get("histograms"):
        lines.append(f"{'Histogram':<38}{'Count':>8}"
                     f"{'Avg(' + time_unit + ')':>11}"
                     f"{'Min':>10}{'Max':>11}")
        for name in sorted(snap["histograms"]):
            st = snap["histograms"][name]
            lines.append(
                f"{name[:37]:<38}{st['count']:>8}{st['avg'] * scale:>11.3f}"
                f"{st['min'] * scale:>10.3f}{st['max'] * scale:>11.3f}")
        lines.append("-" * width)
    if snap.get("events"):
        lines.append(f"events: {len(snap['events'])} "
                     f"(last: {snap['events'][-1].get('event')})")
        lines.append("-" * width)
    if len(lines) == 3:
        lines.append("(no stats recorded)")
        lines.append("-" * width)
    return "\n".join(lines)


def export_json(path: str) -> str:
    """Write snapshot() as a JSON artifact."""
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(snapshot(), f, indent=1, default=str)
    return path


def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    n = "".join(out)
    if n and n[0].isdigit():
        n = "_" + n
    return "paddle_tpu_" + n


def _prom_uniq(pn: str, seen: Dict[str, int]) -> str:
    """Sanitization can collide distinct metric names (`span.a.b` and
    `span.a_b` both map to `..._span_a_b`); a duplicate family is a
    format violation, so later arrivals get a deterministic suffix."""
    n = seen.get(pn, 0)
    seen[pn] = n + 1
    return pn if n == 0 else f"{pn}_dup{n}"


def prometheus_text() -> str:
    """Prometheus text exposition format (text/plain; version 0.0.4).

    Histograms emit the full conforming family — cumulative
    `_bucket{le=...}` including `le="+Inf"`, `_sum`, `_count` — plus a
    sibling `<name>_q` summary family carrying the sketch quantiles
    (p50/p95/p99 at <=1% relative error). The summary is a separate
    family because mixing sample types under one metric name is
    non-conforming."""
    snap = _REGISTRY.snapshot()
    seen: Dict[str, int] = {}
    lines: List[str] = []
    for name in sorted(snap["counters"]):
        pn = _prom_uniq(_prom_name(name), seen)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {snap['counters'][name]}")
    for name in sorted(snap["gauges"]):
        pn = _prom_uniq(_prom_name(name), seen)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {snap['gauges'][name]}")
    for name in sorted(snap["histograms"]):
        st = snap["histograms"][name]
        pn = _prom_uniq(_prom_name(name), seen)
        lines.append(f"# TYPE {pn} histogram")
        for ub, c in st["buckets"].items():
            lines.append(f'{pn}_bucket{{le="{ub}"}} {c}')
        lines.append(f'{pn}_bucket{{le="+Inf"}} {st["count"]}')
        lines.append(f"{pn}_sum {st['sum']}")
        lines.append(f"{pn}_count {st['count']}")
        if "p50" in st:
            qn = _prom_uniq(pn + "_q", seen)
            lines.append(f"# TYPE {qn} summary")
            for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                lines.append(f'{qn}{{quantile="{q}"}} {st[key]}')
            lines.append(f"{qn}_sum {st['sum']}")
            lines.append(f"{qn}_count {st['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def export_prometheus(path: str) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write(prometheus_text())
    return path


def _prom_escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def prometheus_text_multi(per_source: Dict[str, Dict[str, Any]]) -> str:
    """ONE fleet-wide Prometheus scrape over many sources' snapshots
    (snapshot()- or mergeable_snapshot()-shaped, keyed by source name).

    The multi-source fix: the same metric from N sources becomes N samples
    of ONE family distinguished by a `source` label — NOT N name-mangled
    `_dup` families (the single-process `_prom_uniq` collision rule stays
    for sanitization collisions WITHIN a source, where no label can help).

    Histograms additionally emit a fleet-wide `<name>_q` summary family
    (no source label): quantiles of the bin-wise MERGED sketch, the true
    fleet p50/p95/p99 that per-source quantiles cannot be averaged into.
    Requires mergeable (sketch_payload) histogram entries; stats()-shaped
    entries still export their per-source bucket family."""
    # family order: union of names, counters then gauges then histograms,
    # one TYPE line per family with every source's sample under it
    names: Dict[str, List[str]] = {"counters": [], "gauges": [],
                                   "histograms": []}
    for kind in names:
        seen_names = set()
        for snap in per_source.values():
            seen_names.update((snap.get(kind) or {}).keys())
        names[kind] = sorted(seen_names)
    # sanitization collisions within the union get the _dup suffix once,
    # consistently across sources (same raw name -> same family)
    seen: Dict[str, int] = {}
    fam: Dict[Tuple[str, str], str] = {}
    for kind in ("counters", "gauges", "histograms"):
        for name in names[kind]:
            fam[(kind, name)] = _prom_uniq(_prom_name(name), seen)
    lines: List[str] = []
    sources = sorted(per_source)
    for name in names["counters"]:
        pn = fam[("counters", name)]
        lines.append(f"# TYPE {pn} counter")
        for src in sources:
            vals = per_source[src].get("counters") or {}
            if name in vals:
                lines.append(f'{pn}{{source="{_prom_escape(src)}"}} '
                             f"{vals[name]}")
    for name in names["gauges"]:
        pn = fam[("gauges", name)]
        lines.append(f"# TYPE {pn} gauge")
        for src in sources:
            vals = per_source[src].get("gauges") or {}
            if name in vals:
                lines.append(f'{pn}{{source="{_prom_escape(src)}"}} '
                             f"{vals[name]}")
    merged_q: List[Tuple[str, Histogram]] = []
    for name in names["histograms"]:
        pn = fam[("histograms", name)]
        lines.append(f"# TYPE {pn} histogram")
        merged: Optional[Histogram] = None
        for src in sources:
            entry = (per_source[src].get("histograms") or {}).get(name)
            if entry is None:
                continue
            if isinstance(entry, Histogram):
                entry = entry.sketch_payload()
            lab = f'source="{_prom_escape(src)}"'
            if "bins" in entry:       # mergeable form
                buckets = dict(zip(entry.get("buckets") or (),
                                   entry.get("bucket_counts") or ()))
                count, total = entry.get("count", 0), entry.get("sum", 0.0)
                if merged is None:
                    merged = Histogram.from_payload(name, entry)
                else:
                    merged.merge(entry)
            else:                     # stats() form: no merged quantiles
                buckets = entry.get("buckets") or {}
                count, total = entry.get("count", 0), entry.get("sum", 0.0)
            for ub, c in buckets.items():
                lines.append(f'{pn}_bucket{{le="{ub}",{lab}}} {c}')
            lines.append(f'{pn}_bucket{{le="+Inf",{lab}}} {count}')
            lines.append(f"{pn}_sum{{{lab}}} {total}")
            lines.append(f"{pn}_count{{{lab}}} {count}")
        if merged is not None:
            merged_q.append((pn, merged))
    for pn, merged in merged_q:
        qn = _prom_uniq(pn + "_q", seen)
        lines.append(f"# TYPE {qn} summary")
        for q in (0.5, 0.95, 0.99):
            lines.append(f'{qn}{{quantile="{q}"}} {merged.quantile(q)}')
        lines.append(f"{qn}_sum {merged.sum}")
        lines.append(f"{qn}_count {merged.count}")
    return "\n".join(lines) + ("\n" if lines else "")


# ---- CLI: the CI-artifact inspection tool ----------------------------------
# `python -m paddle_tpu.monitor show|diff|trace ...` — pretty-print a
# snapshot JSON (or flight-recorder dump), diff two snapshots (what did
# this run do that the good run didn't?), and convert a flight-recorder
# dump into a chrome://tracing file.

def _load_artifact(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def _is_flight_dump(doc: Dict[str, Any]) -> bool:
    return str(doc.get("schema", "")).startswith("paddle_tpu.flight_recorder")


def _render_flight_dump(doc: Dict[str, Any]) -> str:
    lines = ["-" * 78,
             f"flight recorder dump — reason: {doc.get('reason')!r}  "
             f"rank {doc.get('rank')}  pid {doc.get('pid')}",
             "-" * 78,
             f"in-flight phase: {doc.get('inflight_phase')!r}"]
    # schema /4 correlated-incident identity (absent in /1–/3 dumps)
    if doc.get("incident_id") or doc.get("source"):
        lines.insert(3, f"incident: {doc.get('incident_id') or '-'}  "
                        f"source: {doc.get('source') or '-'}")
    steps = doc.get("steps", [])
    open_step = doc.get("open_step")
    lines.append(f"step records: {len(steps)}"
                 + (" (+1 open/in-flight)" if open_step else ""))
    for rec in ([open_step] if open_step else []) + steps[-3:][::-1]:
        phases = ", ".join(f"{k}={v * 1e3:.2f}ms"
                           for k, v in sorted(rec.get("phases", {}).items(),
                                              key=lambda kv: -kv[1]))
        tag = "OPEN " if rec is open_step else ""
        wall = rec.get("wall")
        lines.append(f"  {tag}step {rec.get('step')}: "
                     f"wall={wall * 1e3:.2f}ms " if wall is not None
                     else f"  {tag}step {rec.get('step')} (unfinished) ")
        if phases:
            lines[-1] += f"[{phases}]"
        if rec.get("error"):
            lines.append(f"    error: {rec['error']}")
    evs = doc.get("events", [])
    if evs:
        lines.append(f"events ({len(evs)}, newest last):")
        for ev in evs[-8:]:
            extra = {k: v for k, v in ev.items() if k not in ("ts", "event")}
            lines.append(f"  {ev.get('event')} {extra}")
    colls = doc.get("collectives", [])
    if colls:
        lines.append(f"recent collectives ({len(colls)}): "
                     + ", ".join(f"{c[1]}({c[2]}B)" for c in colls[-8:]))
    counters = doc.get("monitor", {}).get("counters", {})
    if counters:
        lines.append(f"monitor counters: {len(counters)} "
                     f"(use `show` on a snapshot export for the full table)")
    # schema /2 memory section (a /1 dump simply has none of these keys)
    mem_lines = _render_dump_memory(doc)
    if mem_lines:
        lines.extend(mem_lines)
    # schema /3 trace + SLO sections (older dumps simply lack the keys)
    lines.extend(_render_dump_traces(doc))
    slosec = doc.get("slo")
    if slosec:
        from .obs import slo as _slo
        lines.extend(_slo.render_slo(slosec).splitlines())
    # schema /5 sync section (older dumps simply lack the key); one
    # summary line here — `monitor threads <dump>` renders the full table
    syncsec = doc.get("sync")
    if syncsec and syncsec.get("enabled"):
        nviol = len(syncsec.get("violations") or [])
        lines.append(f"sync: {len(syncsec.get('threads') or [])} registered "
                     f"threads, {len(syncsec.get('lock_order') or [])} "
                     f"lock-order edges, {nviol} violation(s)"
                     + (" — see `monitor threads <dump>`" if nviol else ""))
    lines.append("-" * 78)
    return "\n".join(lines)


def _render_dump_traces(doc: Dict[str, Any]) -> List[str]:
    """Render the schema-/3 trace ring of a flight dump: tail-sampled
    request traces (protected bad traces first) as span waterfalls.
    Returns [] for a /1 or /2 dump — `show` stays version-agnostic."""
    tracesec = doc.get("traces") or {}
    kept = tracesec.get("kept") or []
    ring = tracesec.get("ring") or []
    if not kept and not ring:
        return []
    from .obs import trace as _trace
    lines = [f"request traces: {len(ring)} in ring, "
             f"{len(kept)} kept (bad/slow, evict-protected)"]
    lines.extend(_trace.render_traces(kept + ring).splitlines())
    return lines


def _render_dump_memory(doc: Dict[str, Any]) -> List[str]:
    """Render the schema-/2 memory section of a flight dump: last census,
    phase peaks, and (OOM dumps) top buffers + per-executable temp bytes.
    Returns [] for a /1 dump — `show` stays version-agnostic."""
    from .obs import memory as _memory
    lines: List[str] = []
    memsec = doc.get("memory") or {}
    oom = (doc.get("extra") or {}).get("memory") or {}
    census = oom.get("census_at_dump") or \
        (memsec.get("census") or [None])[-1]
    if census:
        tags = census.get("tags", {})
        shares = ", ".join(
            f"{n}={_memory._fmt_bytes(tags[n]['bytes'])}"
            for n in sorted(tags, key=lambda n: -tags[n]["bytes"])[:6])
        lines.append(
            f"memory census ({len(memsec.get('census') or [])} in ring): "
            f"total {_memory._fmt_bytes(census.get('total_bytes', 0))}"
            + (f" [{shares}]" if shares else ""))
    peaks = oom.get("phase_peaks") or memsec.get("phase_peaks") or {}
    if peaks:
        lines.append("phase HBM peaks: " + ", ".join(
            f"{k}={_memory._fmt_bytes(v)}"
            for k, v in sorted(peaks.items(), key=lambda kv: -kv[1])))
    for row in (oom.get("top_buffers") or [])[:8]:
        origin = f" ({row['origin']})" if row.get("origin") else ""
        lines.append(f"  top buffer {_memory._fmt_bytes(row['bytes'])}  "
                     f"{row.get('dtype')}{row.get('shape')}  "
                     f"tag={row.get('tag')}{origin}")
    for name, rep in (oom.get("executables") or {}).items():
        if isinstance(rep, dict) and rep:
            body = ", ".join(f"{k}={v}" for k, v in sorted(rep.items()))
            lines.append(f"  executable {name}: {body}")
    return lines


def _diff_snapshots(a: Dict[str, Any], b: Dict[str, Any]) -> str:
    """b - a for counters/gauges and histogram count/sum: what happened
    between the two exports."""
    lines = ["-" * 78, f"{'monitor diff (b - a)':<52}{'a':>8}{'b':>9}{'Δ':>9}",
             "-" * 78]
    for kind in ("counters", "gauges"):
        ka, kb = a.get(kind, {}), b.get(kind, {})
        names = sorted(set(ka) | set(kb))
        rows = []
        for n in names:
            va, vb = ka.get(n, 0), kb.get(n, 0)
            if va != vb:
                rows.append((n, va, vb))
        if rows:
            lines.append(kind + ":")
            for n, va, vb in rows:
                try:
                    delta = f"{vb - va:+}"
                except TypeError:
                    delta = "?"
                lines.append(f"  {n[:49]:<50}{va:>8}{vb:>9}{delta:>9}")
    ha, hb = a.get("histograms", {}), b.get("histograms", {})
    rows = []
    for n in sorted(set(ha) | set(hb)):
        ca = ha.get(n, {}).get("count", 0)
        cb = hb.get(n, {}).get("count", 0)
        if ca != cb:
            rows.append(f"  {n[:49]:<50}{ca:>8}{cb:>9}{cb - ca:>+9}")
    if rows:
        lines.append("histogram counts:")
        lines.extend(rows)
    if len(lines) == 3:
        lines.append("(no differences)")
    lines.append("-" * 78)
    return "\n".join(lines)


def _slo_main(args) -> int:
    """`python -m paddle_tpu.monitor slo [path]` — render burn rates and
    latency quantiles from a flight dump's `slo` section, a snapshot's
    `slo.*` gauges, or (no path) this process's live SLO plane."""
    from .obs import slo as _slo
    if args.path is None:
        print(_slo.render_slo(_slo.stats()))
        return 0
    doc = _load_artifact(args.path)
    if _is_flight_dump(doc):
        print(_slo.render_slo(doc.get("slo")))
        return 0
    print(_slo.render_slo(_slo.doc_from_snapshot(doc)))
    return 0


def _fleet_main(args) -> int:
    """`python -m paddle_tpu.monitor fleet [path] [--probe HOST:PORT ...]`
    — render the replica table from a flight dump's `fleet` section
    (FleetRouter.dump), or build one live by probing each `--probe`
    replica's 'PDHQ' endpoint."""
    import sys as _sys
    from .serving.fleet import render_fleet
    if args.probe:
        from .inference.server import PredictorClient
        doc = {"fleet": "probe", "replicas": {}}
        for i, spec in enumerate(args.probe):
            host, _, port = spec.rpartition(":")
            row = {"host": host or "127.0.0.1", "port": int(port),
                   "healthy": False, "draining": False, "score": 0.0,
                   "served": 0, "failures": 0, "queue_depth": 0,
                   "warm_start_ms": None, "tenants": []}
            try:
                c = PredictorClient(row["host"], row["port"],
                                    connect_timeout=2.0, max_retries=0)
                s = c.health(deadline_ms=3000)
                c.close()
                rid = s.get("replica_id", i)
                row.update(healthy=True,
                           draining=bool(s.get("draining")),
                           queue_depth=s.get("queue_depth", 0),
                           warm_start_ms=s.get("warm_start_ms"),
                           tenants=sorted((s.get("tenants") or {}).keys()))
            except Exception as e:
                rid = i
                row["error"] = f"{type(e).__name__}"
            doc["replicas"][str(rid)] = row
        print(render_fleet(doc))
        return 0
    if args.path is None:
        print("error: pass a flight dump path or --probe HOST:PORT",
              file=_sys.stderr)
        return 2
    doc = _load_artifact(args.path)
    # FleetRouter.dump passes the snapshot via the recorder's `extra`
    # channel, which lands under "extra" in the artifact
    fleet_doc = doc.get("fleet") or (doc.get("extra") or {}).get("fleet")
    print(render_fleet(fleet_doc))
    return 0


def _cache_main(args) -> int:
    """`python -m paddle_tpu.monitor cache [dir] [--gc] [--verify]`."""
    from .core import compile_cache as _cc
    d = args.dir or _cc.cache_dir()
    if not d:
        import sys as _sys
        print("error: no cache dir (pass one or set "
              "FLAGS_compile_cache_dir)", file=_sys.stderr)
        return 2
    if args.verify:
        ok, bad = _cc.verify(d)
        print(f"verify: {ok} ok, {len(bad)} corrupt pruned")
        for key in bad:
            print(f"  pruned {key}")
    if args.gc:
        evicted = _cc.gc(d, cap_mb=args.cap_mb)
        print(f"gc: {len(evicted)} entries evicted")
        for key in evicted:
            print(f"  evicted {key}")
    rows = _cc.entries(d)
    total = sum(max(0, r["disk_bytes"]) for r in rows)
    print(f"compile cache {d}: {len(rows)} entries, "
          f"{total / 1e6:.1f} MB")
    print(f"{'key':<42} {'kind':<14} {'topology':<18} "
          f"{'bytes':>10} {'age':>8} {'hits':>5}")
    for r in rows:
        age = r["age_s"]
        age_s = f"{age / 3600:.1f}h" if age >= 3600 else f"{age:.0f}s"
        print(f"{r['key']:<42} {r.get('kind', ''):<14} "
              f"{r.get('topology', ''):<18} {r['disk_bytes']:>10} "
              f"{age_s:>8} {r.get('hits', 0):>5}")
    return 0


def _ps_main(args) -> int:
    """`python -m paddle_tpu.monitor ps <wal-dir>`: render a PS
    durability directory — snapshot generations, the WAL segment chain
    (per-segment intactness), and the HA role/watermark side-file."""
    import sys as _sys
    if not os.path.isdir(args.dir):
        print(f"error: {args.dir} is not a directory", file=_sys.stderr)
        return 2
    from .distributed.ps.wal import wal_status
    doc = wal_status(args.dir)
    print(f"ps durability dir {doc['dir']}: last_lsn={doc['last_lsn']}")
    snap = doc.get("snapshot")
    if snap:
        tables = ", ".join(snap["tables"]) or "-"
        print(f"snapshot: v{snap['version']} @ lsn {snap['lsn']} "
              f"(tables: {tables})")
        if snap.get("bak_version") is not None:
            print(f"  previous generation (.bak): v{snap['bak_version']} "
                  f"@ lsn {snap['bak_lsn']}")
    else:
        print("snapshot: none (recovery would replay the WAL from lsn 0)")
    segs = doc["segments"]
    print(f"wal segments: {len(segs)}")
    if segs:
        print(f"  {'file':<24} {'start':>8} {'last':>8} {'records':>8} "
              f"{'bytes':>10}  state")
        for s in segs:
            last = s["last_lsn"] if s["last_lsn"] is not None else "-"
            state = "intact" if s["intact"] else "TORN (truncates at replay)"
            print(f"  {s['file']:<24} {s['start_lsn']:>8} {last:>8} "
                  f"{s['records']:>8} {s['bytes']:>10}  {state}")
    ha = doc.get("ha")
    if ha:
        print(f"ha: role={ha.get('role')} node={ha.get('node_id')} "
              f"epoch={ha.get('epoch')} applied_lsn={ha.get('applied_lsn')} "
              f"endpoint={ha.get('endpoint')}")
        acks = ha.get("acks") or {}
        for sid, lsn in sorted(acks.items()):
            lag = None
            try:
                lag = int(ha.get("applied_lsn", 0)) - int(lsn)
            except (TypeError, ValueError):
                pass
            lag_s = f" (lag {lag})" if lag is not None else ""
            print(f"  standby {sid}: acked lsn {lsn}{lag_s}")
    return 0


def _main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.monitor",
        description="inspect monitor/flight-recorder CI artifacts")
    sub = p.add_subparsers(dest="cmd", required=True)
    p_show = sub.add_parser(
        "show", help="pretty-print a monitor snapshot JSON or a "
                     "flight-recorder dump; multiple paths render a "
                     "correlated-incident group (sorted by source)")
    p_show.add_argument("path", nargs="+")
    p_diff = sub.add_parser(
        "diff", help="diff two monitor snapshot JSONs (b - a)")
    p_diff.add_argument("a")
    p_diff.add_argument("b")
    p_trace = sub.add_parser(
        "trace", help="convert a flight-recorder dump to a chrome trace")
    p_trace.add_argument("dump")
    p_trace.add_argument("-o", "--out", default=None,
                         help="output path (default: <dump>.trace.json)")
    p_mem = sub.add_parser(
        "mem", help="render a flight-recorder dump's memory census "
                    "(no path: take a live census of this process)")
    p_mem.add_argument("path", nargs="?", default=None)
    p_slo = sub.add_parser(
        "slo", help="render SLO state: error-budget burn rates, bad-request "
                    "breakdown, sketch latency quantiles (from a "
                    "flight-recorder dump, a monitor snapshot's slo.* "
                    "gauges, or — with no path — this live process)")
    p_slo.add_argument("path", nargs="?", default=None)
    p_fleet = sub.add_parser(
        "fleet", help="render a fleet replica table: from a flight dump's "
                      "`fleet` section (FleetRouter.dump), or live via "
                      "--probe HOST:PORT health probes")
    p_fleet.add_argument("path", nargs="?", default=None)
    p_fleet.add_argument("--probe", action="append", default=[],
                         metavar="HOST:PORT",
                         help="probe a replica's 'PDHQ' endpoint "
                              "(repeatable)")
    p_cache = sub.add_parser(
        "cache", help="inspect a persistent compile-cache directory "
                      "(core/compile_cache.py): list entries; --gc to "
                      "enforce the size cap, --verify to CRC-check and "
                      "prune corrupt entries")
    p_cache.add_argument("dir", nargs="?", default=None,
                         help="cache directory (default: "
                              "FLAGS_compile_cache_dir)")
    p_cache.add_argument("--gc", action="store_true",
                         help="evict LRU entries beyond FLAGS_compile_cache_mb")
    p_cache.add_argument("--cap-mb", type=float, default=None,
                         help="override the size cap for --gc")
    p_cache.add_argument("--verify", action="store_true",
                         help="CRC-check every entry and prune corrupt ones")
    p_top = sub.add_parser(
        "top", help="live fleet table from a TelemetryCollector: per-source "
                    "qps / queue / p99 / burn / HBM / role, stragglers "
                    "highlighted (obs/telemetry.py)")
    p_top.add_argument("addr", help="collector HOST:PORT (the address it "
                                    "published in the TCPStore)")
    p_top.add_argument("-n", "--iterations", type=int, default=1,
                       help="refresh N times (default 1: one-shot)")
    p_top.add_argument("--interval", type=float, default=1.0,
                       help="seconds between refreshes")
    p_threads = sub.add_parser(
        "threads", help="render the thread/lock table: registered threads "
                        "with owners, held locks, the observed lock-order "
                        "graph, and recorded order violations — from a "
                        "flight dump's `sync` section, or (no path) this "
                        "live process (utils/syncwatch.py)")
    p_threads.add_argument("path", nargs="?", default=None)
    p_threads.add_argument("--hold-warn-ms", type=float, default=None,
                           help="dump acquisition stacks for locks held "
                                "longer than this (default: "
                                "FLAGS_sync_hold_warn_ms)")
    p_ps = sub.add_parser(
        "ps", help="render a parameter-server durability directory "
                   "(distributed/ps/wal.py): snapshot generations, WAL "
                   "segment chain with intactness, HA role + replication "
                   "watermark")
    p_ps.add_argument("dir", help="a PsServer wal_dir (FLAGS_ps_wal_dir)")
    args = p.parse_args(argv)
    if args.cmd == "top":
        from .obs import telemetry as _telemetry
        host, _, port = args.addr.rpartition(":")
        for i in range(max(1, args.iterations)):
            if i:
                time.sleep(args.interval)
            doc = _telemetry.query_collector(host or "127.0.0.1", int(port))
            print(_telemetry.render_top(doc))
        return 0
    if args.cmd == "threads":
        from .utils import syncwatch as _syncwatch
        if args.path is None:
            print(_syncwatch.render_threads(hold_warn_ms=args.hold_warn_ms))
            return 0
        doc = _load_artifact(args.path)
        if not _is_flight_dump(doc):
            print(f"error: {args.path} is not a flight-recorder dump "
                  f"(schema: {doc.get('schema')!r})")
            return 2
        syncsec = doc.get("sync")
        if not syncsec:
            print(f"no sync section in dump "
                  f"(schema: {doc.get('schema')!r} — /1–/4 dumps predate "
                  "it, or the dumping process ran without FLAGS_sync_watch)")
            return 0
        print(_syncwatch.render_threads(syncsec,
                                        hold_warn_ms=args.hold_warn_ms))
        return 0
    if args.cmd == "ps":
        return _ps_main(args)
    if args.cmd == "cache":
        return _cache_main(args)
    if args.cmd == "fleet":
        return _fleet_main(args)
    if args.cmd == "slo":
        return _slo_main(args)
    if args.cmd == "show":
        docs = [(pth, _load_artifact(pth)) for pth in args.path]
        if len(docs) > 1:
            # incident-group rendering: sort by source so the same fleet
            # reads the same top-to-bottom every time
            docs.sort(key=lambda pd: str(pd[1].get("source") or pd[0]))
            ids = {d.get("incident_id") for _, d in docs
                   if d.get("incident_id")}
            if len(ids) == 1:
                print(f"correlated incident {ids.pop()} "
                      f"({len(docs)} dumps):")
        for pth, doc in docs:
            if _is_flight_dump(doc):
                print(_render_flight_dump(doc))
            else:
                print(render_snapshot(doc, title_right=f"({pth})"))
        return 0
    if args.cmd == "diff":
        print(_diff_snapshots(_load_artifact(args.a),
                              _load_artifact(args.b)))
        return 0
    if args.cmd == "trace":
        doc = _load_artifact(args.dump)
        if not _is_flight_dump(doc):
            print(f"error: {args.dump} is not a flight-recorder dump "
                  f"(schema: {doc.get('schema')!r})")
            return 2
        from .obs import dump_to_chrome_events
        out = args.out or (args.dump + ".trace.json")
        events = dump_to_chrome_events(doc)
        os.makedirs(os.path.dirname(os.path.abspath(out)) or ".",
                    exist_ok=True)
        with open(out, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        print(out)
        return 0
    if args.cmd == "mem":
        from .obs import memory as _memory
        if args.path is None:
            print(_memory.render_census(
                _memory.census(publish=False, store=False),
                top=_memory.top_buffers()))
            return 0
        doc = _load_artifact(args.path)
        if not _is_flight_dump(doc):
            print(f"error: {args.path} is not a flight-recorder dump "
                  f"(schema: {doc.get('schema')!r})")
            return 2
        oom = (doc.get("extra") or {}).get("memory") or {}
        memsec = doc.get("memory") or {}
        census = oom.get("census_at_dump") or \
            (memsec.get("census") or [None])[-1]
        if not census:
            print(f"no memory census in dump "
                  f"(schema: {doc.get('schema')!r} — /1 dumps predate the "
                  "memory section, or FLAGS_mem_census was off)")
            return 0
        print(_memory.render_census(census, top=oom.get("top_buffers")))
        for name, rep in (oom.get("executables") or {}).items():
            if isinstance(rep, dict) and rep:
                body = ", ".join(f"{k}={v}" for k, v in sorted(rep.items()))
                print(f"executable {name}: {body}")
        return 0
    return 2


if __name__ == "__main__":
    import sys as _sys
    _sys.exit(_main())
