"""Adam family + Lamb + classic optimizers.

Reference parity: `python/paddle/optimizer/{adam,adamw,lamb,adagrad,rmsprop,
adadelta,adamax}.py` over the fluid adam/lamb kernels
(`operators/optimizers/adam_op.h`, `lamb_op.h`).
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=None, grad_clip=None, name=None,
                 lazy_mode=False, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lazy_mode = lazy_mode

    def _create_slots(self, p):
        return {"moment1": jnp.zeros_like(p._value, dtype=jnp.float32),
                "moment2": jnp.zeros_like(p._value, dtype=jnp.float32)}

    def _apply(self, p, g, slots, *, lr, t, wd):
        g32 = g.astype(jnp.float32)
        if wd and not self._decoupled():
            g32 = g32 + wd * p.astype(jnp.float32)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * (g32 * g32)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + self._epsilon)
        if wd and self._decoupled():
            upd = upd + wd * p.astype(jnp.float32)
        p_new = (p.astype(jnp.float32) - lr * upd).astype(p.dtype)
        return p_new, {"moment1": m, "moment2": v}

    def _decoupled(self):
        return False

    def _apply_sparse(self, p, sr, slots, *, lr, t, wd):
        """Sparse adam (adam_op.h SelectedRows path). lazy_mode=True:
        moments and weights update ONLY at the gradient's rows (untouched
        rows keep stale moments). Default lazy_mode=False matches the
        reference default: densify so every row's moments decay each step."""
        if not self._lazy_mode:
            return super()._apply_sparse(p, sr, slots, lr=lr, t=t, wd=wd)
        rows = sr.rows
        g32 = sr.values.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if wd and not self._decoupled():
            g32 = g32 + wd * p32[rows]
        m_r = self._beta1 * slots["moment1"][rows] + (1 - self._beta1) * g32
        v_r = self._beta2 * slots["moment2"][rows] + (1 - self._beta2) * (g32 * g32)
        mhat = m_r / (1 - self._beta1 ** t)
        vhat = v_r / (1 - self._beta2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + self._epsilon)
        if wd and self._decoupled():
            upd = upd + wd * p32[rows]
        p_new = p32.at[rows].add(-lr * upd).astype(p.dtype)
        return p_new, {"moment1": slots["moment1"].at[rows].set(m_r),
                       "moment2": slots["moment2"].at[rows].set(v_r)}


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, name, lazy_mode=lazy_mode)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled(self):
        return True


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_slots(self, p):
        return {"moment": jnp.zeros_like(p._value, dtype=jnp.float32),
                "inf_norm": jnp.zeros_like(p._value, dtype=jnp.float32)}

    def _apply(self, p, g, slots, *, lr, t, wd):
        g32 = g.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * p.astype(jnp.float32)
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(g32))
        p_new = (p.astype(jnp.float32)
                 - (lr / (1 - self._beta1 ** t)) * m / (u + self._epsilon)).astype(p.dtype)
        return p_new, {"moment": m, "inf_norm": u}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _param_wd(self, p):
        if self._exclude_fn is not None and self._exclude_fn(p):
            return 0.0
        return self._weight_decay

    def _create_slots(self, p):
        return {"moment1": jnp.zeros_like(p._value, dtype=jnp.float32),
                "moment2": jnp.zeros_like(p._value, dtype=jnp.float32)}

    def _apply(self, p, g, slots, *, lr, t, wd):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * (g32 * g32)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + wd * p32
        w_norm = jnp.sqrt(jnp.sum(p32 * p32))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (p32 - lr * trust * r).astype(p.dtype), {"moment1": m, "moment2": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_slots(self, p):
        return {"moment": jnp.full_like(p._value, self._init_acc, dtype=jnp.float32)}

    def _apply(self, p, g, slots, *, lr, t, wd):
        g32 = g.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * p.astype(jnp.float32)
        acc = slots["moment"] + g32 * g32
        p_new = (p.astype(jnp.float32) - lr * g32 / (jnp.sqrt(acc) + self._epsilon)).astype(p.dtype)
        return p_new, {"moment": acc}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_slots(self, p):
        s = {"mean_square": jnp.zeros_like(p._value, dtype=jnp.float32),
             "momentum": jnp.zeros_like(p._value, dtype=jnp.float32)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(p._value, dtype=jnp.float32)
        return s

    def _apply(self, p, g, slots, *, lr, t, wd):
        g32 = g.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * p.astype(jnp.float32)
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * g32 * g32
        out = {"mean_square": ms}
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
            out["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * slots["momentum"] + lr * g32 / denom
        out["momentum"] = mom
        return (p.astype(jnp.float32) - mom).astype(p.dtype), out


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon

    def _create_slots(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p._value, dtype=jnp.float32),
                "avg_squared_update": jnp.zeros_like(p._value, dtype=jnp.float32)}

    def _apply(self, p, g, slots, *, lr, t, wd):
        g32 = g.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * p.astype(jnp.float32)
        asg = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * g32 * g32
        upd = g32 * jnp.sqrt(slots["avg_squared_update"] + self._epsilon) / \
            jnp.sqrt(asg + self._epsilon)
        asu = self._rho * slots["avg_squared_update"] + (1 - self._rho) * upd * upd
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), \
            {"avg_squared_grad": asg, "avg_squared_update": asu}


class LarsMomentum(Optimizer):
    """LARS (layer-wise adaptive rate scaling) momentum.

    Reference parity: `operators/optimizers/lars_momentum_op.cc` /
    `fluid/optimizer.py` LarsMomentumOptimizer: local_lr = lr *
    lars_coeff * ||w|| / (||g|| + lars_weight_decay * ||w||), then
    momentum update with that per-layer lr.
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=1e-3,
                 lars_weight_decay=5e-4, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=1e-9, name=None):
        super().__init__(learning_rate, parameters, lars_weight_decay,
                         grad_clip, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._epsilon = epsilon
        # name-substring exclusion list (reference LarsMomentumOptimizer's
        # exclude_from_weight_decay; same role as Lamb's exclude fn)
        self._exclude_wd = list(exclude_from_weight_decay or [])

    def _param_wd(self, p):
        pname = p.name or ""
        if any(s in pname for s in self._exclude_wd):
            return 0.0
        return self._weight_decay

    def _create_slots(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _apply(self, p, g, slots, *, lr, t, wd):
        p32, g32 = p.astype(jnp.float32), g.astype(jnp.float32)
        wn = jnp.sqrt(jnp.sum(p32 * p32))
        gn = jnp.sqrt(jnp.sum(g32 * g32))
        local_lr = lr * self._lars_coeff * wn / (
            gn + wd * wn + self._epsilon)
        # scalar params (biases/norms): no layer adaptation (reference
        # excludes them); wn==0 guards fresh zeros too
        local_lr = jnp.where(wn > 0, local_lr, lr)
        v = self._momentum * slots["velocity"] + local_lr * (g32 + wd * p32)
        return (p32 - v).astype(p.dtype), {"velocity": v}
