"""Optimizer base + SGD/Momentum.

Reference parity: `python/paddle/optimizer/optimizer.py` (modern API) over the
fluid optimizer ops (`operators/optimizers/sgd_op.cc`, `momentum_op.cc`,
`merged_adam` multi-tensor).

TPU-first design: `step()` applies ONE jitted, fused update over all
parameters at once (the multi-tensor "merged" optimizer the reference only
has for adam) — gradient clip, weight decay, the update rule, and (when a
GradScaler drives the step) the unscale + found_inf gate all fuse into a
single DONATED XLA executable per parameter-group structure: param and slot
buffers are donated (reused in place, no per-step re-allocation), the step
counter `t` rides as device carry state, and the learning rate enters as a
cached device scalar — steady state pays ONE dispatch and ZERO host→device
scalar transfers per step. The same pure `_apply` core is reused by the
jitted train-step builder (paddle_tpu.jit) so eager and static training
share optimizer semantics.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue
from ..obs import memory as _mem


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list: Optional[List[Parameter]] = parameters
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if weight_decay is None:
            self._weight_decay = 0.0
        elif isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
        else:  # L2Decay object
            self._weight_decay = float(getattr(weight_decay, "_coeff", 0.0))
        self._accumulators: Dict[int, Dict[str, jnp.ndarray]] = {}
        self._step_count = 0
        self._jit_cache = {}
        # fused eager-step state: donated executables per structure key,
        # cached lr device scalar, and the device step-counter carry (host
        # mirror `_t_host` detects external _step_count writes — rollback,
        # set_state_dict — and refreshes the carry)
        self._fused_cache = {}
        self._fused_avals = {}  # cache key -> arg avals (memory_report)
        self._lr_arr = None
        self._lr_host = None
        self._t_arr = None
        self._t_host = None
        self._pending = None  # (expected_t, found_inf array): scaler-gated
        #                       step whose commit awaits the found_inf read

    # ---- lr ----
    def get_lr(self) -> float:
        lr = self._learning_rate
        if hasattr(lr, "get_lr"):
            return float(lr.get_lr())
        return float(lr)

    def set_lr(self, value):
        if hasattr(self._learning_rate, "get_lr"):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ---- subclass hooks ----
    def _create_slots(self, p: Parameter) -> Dict[str, jnp.ndarray]:
        return {}

    def _apply(self, p, g, slots, *, lr, t, wd):
        """Pure update rule: arrays in, (new_param, new_slots) out."""
        raise NotImplementedError

    def _apply_sparse(self, p, sr, slots, *, lr, t, wd):
        """Row-wise update for a merged SelectedRows grad (reference sparse
        optimizer kernels, `operators/optimizers/`). Default: densify —
        always correct; SGD/Adam override with true row-wise rules."""
        return self._apply(p, sr.to_dense().astype(p.dtype), slots,
                           lr=lr, t=t, wd=wd)

    def _uses_decoupled_wd(self) -> bool:
        return False

    def _param_wd(self, p) -> float:
        """Per-parameter weight-decay coefficient (0 for excluded params)."""
        fn = getattr(self, "_apply_decay_param_fun", None)
        if fn is not None and not fn(p.name or ""):
            return 0.0
        return self._weight_decay

    # ---- per-step device scalars (no fresh float() feeds) ----
    def _lr_scalar(self):
        """Learning rate as a cached device scalar: the H2D transfer happens
        only when the host value CHANGES (scheduler tick), never per step."""
        lr_val = self.get_lr()
        if lr_val != self._lr_host or self._lr_arr is None:
            self._lr_host = lr_val
            self._lr_arr = jnp.asarray(lr_val, jnp.float32)
        return self._lr_arr

    def _t_scalar(self):
        """Step counter as device carry state: the fused update returns
        t+1 (gated on found_inf), so steady state never re-uploads it. The
        host mirror catches external _step_count writes (set_state_dict,
        guard rollback) and refreshes the carry from the host."""
        expected = float(self._step_count + 1)
        if self._t_arr is None or self._t_host != expected:
            self._t_arr = jnp.asarray(expected, jnp.float32)
            self._t_host = expected
        return self._t_arr

    def _resolve_pending(self):
        """Commit a scaler-gated step once its found_inf flag is read on
        the host. Returns found_inf (True = the update was gated away) or
        None when nothing is pending."""
        if self._pending is None:
            return None
        expected, found_arr = self._pending
        self._pending = None
        found = bool(found_arr)
        if not found:
            self._step_count += 1
            self._t_host = expected + 1.0
        # gated: device t stayed at `expected` (the in-program where), and
        # _t_host already equals expected — carry stays consistent
        return found

    # ---- step ----
    def step(self, inv_scale=None):
        """Apply one fused update. `inv_scale` (internal, set by
        GradScaler.step under FLAGS_amp_fused_update) folds unscale +
        found_inf check + gate into the same executable and returns the
        device found_inf flag; the commit (step_count++) is deferred to
        `_resolve_pending` so no host sync happens before dispatch."""
        from .. import monitor as _monitor
        from .. import obs as _obs
        if not (_monitor._ENABLED or _obs._TL_ENABLED):
            return self._step_impl(inv_scale)
        import time as _time
        _t0 = _time.time()
        try:
            with _obs.phase("optimizer"):
                return self._step_impl(inv_scale)
        finally:
            if _monitor._ENABLED:
                _monitor.count("optimizer.steps")
                _monitor.observe("optimizer.step_dur", _time.time() - _t0)

    def _step_impl(self, inv_scale=None):
        from ..core.selected_rows import SelectedRows
        self._resolve_pending()
        params = [p for p in (self._parameter_list or [])
                  if not p.stop_gradient and p.grad is not None]
        # sparse (SelectedRows) grads take the row-wise path (reference
        # sparse sgd/adam kernels); dense grads go through the fused jit
        sparse = [p for p in params if isinstance(p.grad, SelectedRows)]
        params = [p for p in params if not isinstance(p.grad, SelectedRows)]
        if inv_scale is not None and sparse:
            raise RuntimeError("fused scaler update does not support "
                               "SelectedRows grads — use scaler.unscale_() "
                               "then step() (GradScaler falls back "
                               "automatically)")
        grads = [p.grad._value if isinstance(p.grad, Tensor) else p.grad for p in params]
        clip = self._grad_clip
        clip_in_jit = clip

        merged = []
        if sparse:
            merged = [p.grad.merge() for p in sparse]
            if clip is not None:
                # clip dense+sparse together HERE (eager): a global norm
                # must include the sparse rows' contribution (reference
                # ClipGradByGlobalNorm handles SelectedRows), and per-grad
                # rules apply to the row values directly
                all_g = grads + [m.values for m in merged]
                all_need = tuple(getattr(p, "need_clip", True)
                                 for p in params + sparse)
                all_g = _clip_fn(clip, all_g, all_need)
                grads = all_g[:len(grads)]
                merged = [SelectedRows(m.rows, v, m.height)
                          for m, v in zip(merged, all_g[len(grads):])]
                clip_in_jit = None  # already applied

        lr_s = self._lr_scalar()
        t_s = self._t_scalar()
        for p, sr in zip(sparse, merged):
            if id(p) not in self._accumulators:
                self._accumulators[id(p)] = self._create_slots(p)
            p._value, self._accumulators[id(p)] = self._apply_sparse(
                p._value, sr, self._accumulators[id(p)],
                lr=lr_s * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0), t=t_s,
                wd=self._param_wd(p))
        if not params:
            self._step_count += 1
            self._t_host = None  # sparse path did not advance the carry
            return None

        for p in params:
            if id(p) not in self._accumulators:
                self._accumulators[id(p)] = self._create_slots(p)
        slots = [self._accumulators[id(p)] for p in params]

        wds = tuple(self._param_wd(p) for p in params)
        need_clip = tuple(getattr(p, "need_clip", True) for p in params)
        lrs = tuple(getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
                    for p in params)

        key = (tuple((tuple(p.shape), str(p.dtype)) for p in params), wds, need_clip, lrs,
               type(clip_in_jit).__name__, inv_scale is not None)
        fn = self._fused_cache.get(key)
        if fn is None:
            # ONE donated executable for the whole update: params (0), slots
            # (2) and the t carry (4) are donated, so steady-state stepping
            # re-uses the buffers in place instead of re-allocating per step.
            # grads (1) and lr (3) are NOT donated — grads stay readable
            # until clear_grad, lr is a cached scalar reused across steps.
            fn = jax.jit(
                self._make_fused_update(clip_in_jit, wds, need_clip, lrs,
                                        scaled=inv_scale is not None),
                donate_argnums=(0, 2, 4))
            self._fused_cache[key] = fn
            # arg avals so memory_report() can AOT-lower this executable
            # later without needing live grads
            self._fused_avals[key] = (
                [jax.ShapeDtypeStruct(p._value.shape, p._value.dtype)
                 for p in params],
                [jax.ShapeDtypeStruct(g.shape, g.dtype) for g in grads],
                [{k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                  for k, v in s.items()} for s in slots],
                inv_scale is not None)

        from .. import monitor as _monitor
        from .. import faults as _faults
        if _monitor._ENABLED:
            _monitor.count("optimizer.fused_dispatches")
        try:
            if _faults._ENABLED:
                _faults.check("mem.alloc")
            if inv_scale is None:
                new_vals, new_slots, new_t = fn([p._value for p in params],
                                                grads, slots, lr_s, t_s)
                found = None
            else:
                new_vals, new_slots, new_t, found = fn(
                    [p._value for p in params], grads, slots, lr_s, t_s,
                    inv_scale)
        except Exception as e:
            _mem.maybe_dump_oom(e, executable="fused_optimizer_update",
                                report=lambda: self.memory_report())
            raise
        for p, v, s in zip(params, new_vals, new_slots):
            p._value = v
            self._accumulators[id(p)] = s
        self._t_arr = new_t
        if _mem._ENABLED:
            # the fused call donated the old param/slot/t buffers; claim the
            # replacements for the live-buffer census
            _mem.tag("params", new_vals, origin="Optimizer.step")
            _mem.tag("opt_slots", new_slots, origin="Optimizer.step")
            _mem.tag("step_state", [new_t], origin="Optimizer.step")
        if inv_scale is None:
            self._step_count += 1
            self._t_host = self._t_host + 1.0
        else:
            # deferred commit: whether this step counted is decided by the
            # found_inf flag, read by GradScaler.update (no sync here)
            self._pending = (self._t_host, found)
        return found

    def _make_fused_update(self, clip, wds, need_clip, lrs, scaled=False):
        """The single-block eager update: dtype harmonization, (optional)
        unscale + finite-scan, grad clip, weight decay, per-param rule, and
        the found_inf gate — one traced program, unrolled over the tree.
        The per-param loop below unrolls INSIDE the jitted block (one
        executable), it is not a per-param dispatch."""
        inner = self._make_update(clip, wds, need_clip, lrs)

        def update(values, grads, slots, lr, t, *scale_args):
            if scaled:
                inv = scale_args[0]
                grads = [g * inv.astype(g.dtype) for g in grads]
                finite = jnp.asarray(True)
                for g in grads:  # tpu-lint: disable=fused-update
                    finite = jnp.logical_and(finite,
                                             jnp.all(jnp.isfinite(g)))
                found = jnp.logical_not(finite)
            outs, outslots = inner(values, grads, slots, lr, t)
            if scaled:
                # gate: a non-finite grad set keeps params/slots/t frozen
                outs = [jnp.where(found, v, nv)
                        for v, nv in zip(values, outs)]
                outslots = [{k: jnp.where(found, s[k], ns[k]) for k in ns}
                            for s, ns in zip(slots, outslots)]
                return outs, outslots, jnp.where(found, t, t + 1.0), found
            return outs, outslots, t + 1.0

        return update

    def _make_update(self, clip, wds, need_clip, lrs):
        def update(values, grads, slots, lr, t):
            grads = [g.astype(jnp.float32) if g.dtype != v.dtype and
                     jnp.issubdtype(v.dtype, jnp.floating) else g
                     for g, v in zip(grads, values)]
            grads = _clip_fn(clip, grads, need_clip)
            outs, outslots = [], []
            # unrolls inside ONE traced executable (not per-param dispatch)
            for v, g, s, wd, plr in zip(values, grads, slots, wds, lrs):  # tpu-lint: disable=fused-update
                nv, ns = self._apply(v, g.astype(v.dtype), s, lr=lr * plr, t=t, wd=wd)
                outs.append(nv)
                outslots.append(ns)
            return outs, outslots

        return update

    def memory_report(self):
        """Compiler-reported memory breakdown for every cached fused-update
        executable (obs.executable_memory): {"fused_update": {...},
        "fused_update_scaled": {...}}. AOT-lowers from the arg avals
        recorded at build time, so it needs no live grads; an un-stepped
        optimizer returns {}."""
        from .. import obs as _obs
        out: Dict[str, Dict[str, int]] = {}
        scalar = jax.ShapeDtypeStruct((), jnp.float32)
        for i, (key, fn) in enumerate(self._fused_cache.items()):
            avals = self._fused_avals.get(key)
            if avals is None:
                continue
            p_avals, g_avals, s_avals, scaled = avals
            args = (p_avals, g_avals, s_avals, scalar, scalar)
            if scaled:
                args = args + (scalar,)
            try:
                rep = _obs.executable_memory(fn.lower(*args).compile())
            except Exception:
                continue
            name = "fused_update_scaled" if scaled else "fused_update"
            if name in out:
                name = f"{name}#{i}"
            out[name] = rep
        return out

    def clear_grad(self, set_to_zero=True):
        for p in (self._parameter_list or []):
            p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in (self._parameter_list or [])]

    # ---- state dict ----
    def state_dict(self):
        self._resolve_pending()
        sd = {"step_count": self._step_count, "accumulators": {}}
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                acc = self._accumulators.get(id(p))
                if acc:
                    sd["accumulators"][p.name or str(i)] = {
                        k: np.asarray(v) for k, v in acc.items()}
        if hasattr(self._learning_rate, "state_dict"):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._resolve_pending()
        self._step_count = state_dict.get("step_count", 0)
        accs = state_dict.get("accumulators", {})
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                key = p.name or str(i)
                if key in accs:
                    self._accumulators[id(p)] = {
                        k: jnp.asarray(v) for k, v in accs[key].items()}
        if "LR_Scheduler" in state_dict and hasattr(self._learning_rate, "set_state_dict"):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])

    # functional API for the jitted train-step builder (paddle_tpu.jit)
    def init_state(self, params):
        return [self._create_slots(p) for p in params]

    def functional_update(self, values, grads, slots, lr, t, params_meta=None,
                          grad_clip="default"):
        """Pure update over value arrays.

        `params_meta` supplies the parameter OBJECTS the values belong to, so
        per-param coefficients (weight decay, need_clip, lr scale) align with
        them — required whenever `values` is not the optimizer's full
        `_parameter_list` (e.g. one pipeline stage's slice). `grad_clip=None`
        disables in-update clipping for callers that pre-clip globally.
        """
        params = list(params_meta) if params_meta is not None \
            else (self._parameter_list or [])
        if params and len(params) != len(values):
            from ..core.enforce import InvalidArgumentError
            raise InvalidArgumentError(
                f"functional_update: {len(values)} values but {len(params)} "
                "params\n  [Hint] pass params_meta matching the values")
        wds = tuple(self._param_wd(p) for p in params) if params else (self._weight_decay,) * len(values)
        need_clip = tuple(getattr(p, "need_clip", True) for p in params) or (True,) * len(values)
        clip = self._grad_clip if grad_clip == "default" else grad_clip
        fn = self._make_update(clip, wds, need_clip,
                               tuple(getattr(p, "optimize_attr", {}).get("learning_rate", 1.0) for p in params)
                               or (1.0,) * len(values))
        return fn(values, grads, slots, lr, t)


def _clip_fn(clip, grads, need_clip):
    if clip is None:
        return grads
    if isinstance(clip, ClipGradByValue):
        return [jnp.clip(g, clip.min, clip.max) if nc else g
                for g, nc in zip(grads, need_clip)]
    if isinstance(clip, ClipGradByNorm):
        out = []
        for g, nc in zip(grads, need_clip):
            if not nc:
                out.append(g)
                continue
            n = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
            out.append(g * jnp.minimum(clip.clip_norm / jnp.maximum(n, 1e-12), 1.0).astype(g.dtype))
        return out
    if isinstance(clip, ClipGradByGlobalNorm):
        sq = [jnp.sum(g.astype(jnp.float32) ** 2) for g, nc in zip(grads, need_clip) if nc]
        if not sq:
            return grads
        gn = jnp.sqrt(sum(sq))
        factor = clip.clip_norm / jnp.maximum(gn, clip.clip_norm)
        return [g * factor.astype(g.dtype) if nc else g for g, nc in zip(grads, need_clip)]
    return grads


class SGD(Optimizer):
    def _apply(self, p, g, slots, *, lr, t, wd):
        if wd:
            g = g + wd * p
        return p - lr.astype(p.dtype) * g, slots

    def _apply_sparse(self, p, sr, slots, *, lr, t, wd):
        # true sparse rule (sgd_op.h SelectedRows path): touch only the
        # gradient's rows; wd applies to touched rows only
        vals = sr.values.astype(p.dtype)
        if wd:
            vals = vals + wd * p[sr.rows]
        return p.at[sr.rows].add(-lr.astype(p.dtype) * vals), slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_slots(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _apply(self, p, g, slots, *, lr, t, wd):
        if wd:
            g = g + wd * p
        v = self._momentum * slots["velocity"] + g
        if self._use_nesterov:
            p_new = p - lr.astype(p.dtype) * (g + self._momentum * v)
        else:
            p_new = p - lr.astype(p.dtype) * v
        return p_new, {"velocity": v}
