"""Optimizer base + SGD/Momentum.

Reference parity: `python/paddle/optimizer/optimizer.py` (modern API) over the
fluid optimizer ops (`operators/optimizers/sgd_op.cc`, `momentum_op.cc`,
`merged_adam` multi-tensor).

TPU-first design: `step()` applies ONE jitted, fused update over all
parameters at once (the multi-tensor "merged" optimizer the reference only
has for adam) — gradient clip, weight decay, and the update rule all fuse
into a single XLA program per parameter-group structure. The same pure
`_apply` core is reused by the jitted train-step builder (paddle_tpu.jit)
so eager and static training share optimizer semantics.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list: Optional[List[Parameter]] = parameters
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if weight_decay is None:
            self._weight_decay = 0.0
        elif isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
        else:  # L2Decay object
            self._weight_decay = float(getattr(weight_decay, "_coeff", 0.0))
        self._accumulators: Dict[int, Dict[str, jnp.ndarray]] = {}
        self._step_count = 0
        self._jit_cache = {}

    # ---- lr ----
    def get_lr(self) -> float:
        lr = self._learning_rate
        if hasattr(lr, "get_lr"):
            return float(lr.get_lr())
        return float(lr)

    def set_lr(self, value):
        if hasattr(self._learning_rate, "get_lr"):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ---- subclass hooks ----
    def _create_slots(self, p: Parameter) -> Dict[str, jnp.ndarray]:
        return {}

    def _apply(self, p, g, slots, *, lr, t, wd):
        """Pure update rule: arrays in, (new_param, new_slots) out."""
        raise NotImplementedError

    def _apply_sparse(self, p, sr, slots, *, lr, t, wd):
        """Row-wise update for a merged SelectedRows grad (reference sparse
        optimizer kernels, `operators/optimizers/`). Default: densify —
        always correct; SGD/Adam override with true row-wise rules."""
        return self._apply(p, sr.to_dense().astype(p.dtype), slots,
                           lr=lr, t=t, wd=wd)

    def _uses_decoupled_wd(self) -> bool:
        return False

    def _param_wd(self, p) -> float:
        """Per-parameter weight-decay coefficient (0 for excluded params)."""
        fn = getattr(self, "_apply_decay_param_fun", None)
        if fn is not None and not fn(p.name or ""):
            return 0.0
        return self._weight_decay

    # ---- step ----
    def step(self):
        from .. import monitor as _monitor
        from .. import obs as _obs
        if not (_monitor._ENABLED or _obs._TL_ENABLED):
            return self._step_impl()
        import time as _time
        _t0 = _time.time()
        try:
            with _obs.phase("optimizer"):
                return self._step_impl()
        finally:
            if _monitor._ENABLED:
                _monitor.count("optimizer.steps")
                _monitor.observe("optimizer.step_dur", _time.time() - _t0)

    def _step_impl(self):
        from ..core.selected_rows import SelectedRows
        params = [p for p in (self._parameter_list or [])
                  if not p.stop_gradient and p.grad is not None]
        # sparse (SelectedRows) grads take the row-wise path (reference
        # sparse sgd/adam kernels); dense grads go through the fused jit
        sparse = [p for p in params if isinstance(p.grad, SelectedRows)]
        params = [p for p in params if not isinstance(p.grad, SelectedRows)]
        grads = [p.grad._value if isinstance(p.grad, Tensor) else p.grad for p in params]
        clip = self._grad_clip
        clip_in_jit = clip

        merged = []
        if sparse:
            merged = [p.grad.merge() for p in sparse]
            if clip is not None:
                # clip dense+sparse together HERE (eager): a global norm
                # must include the sparse rows' contribution (reference
                # ClipGradByGlobalNorm handles SelectedRows), and per-grad
                # rules apply to the row values directly
                all_g = grads + [m.values for m in merged]
                all_need = tuple(getattr(p, "need_clip", True)
                                 for p in params + sparse)
                all_g = _clip_fn(clip, all_g, all_need)
                grads = all_g[:len(grads)]
                merged = [SelectedRows(m.rows, v, m.height)
                          for m, v in zip(merged, all_g[len(grads):])]
                clip_in_jit = None  # already applied

        lr_s = jnp.asarray(self.get_lr(), jnp.float32)
        t_s = jnp.asarray(self._step_count + 1, jnp.float32)
        for p, sr in zip(sparse, merged):
            if id(p) not in self._accumulators:
                self._accumulators[id(p)] = self._create_slots(p)
            p._value, self._accumulators[id(p)] = self._apply_sparse(
                p._value, sr, self._accumulators[id(p)],
                lr=lr_s * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0), t=t_s,
                wd=self._param_wd(p))
        if not params:
            self._step_count += 1
            return

        for p in params:
            if id(p) not in self._accumulators:
                self._accumulators[id(p)] = self._create_slots(p)
        slots = [self._accumulators[id(p)] for p in params]

        wds = tuple(self._param_wd(p) for p in params)
        need_clip = tuple(getattr(p, "need_clip", True) for p in params)
        lrs = tuple(getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
                    for p in params)

        key = (tuple((tuple(p.shape), str(p.dtype)) for p in params), wds, need_clip, lrs,
               type(clip_in_jit).__name__)
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = jax.jit(self._make_update(clip_in_jit, wds, need_clip, lrs))
            self._jit_cache[key] = fn

        new_vals, new_slots = fn([p._value for p in params], grads, slots,
                                 lr_s, t_s)
        for p, v, s in zip(params, new_vals, new_slots):
            p._value = v
            self._accumulators[id(p)] = s
        self._step_count += 1

    def _make_update(self, clip, wds, need_clip, lrs):
        def update(values, grads, slots, lr, t):
            grads = [g.astype(jnp.float32) if g.dtype != v.dtype and
                     jnp.issubdtype(v.dtype, jnp.floating) else g
                     for g, v in zip(grads, values)]
            grads = _clip_fn(clip, grads, need_clip)
            outs, outslots = [], []
            for v, g, s, wd, plr in zip(values, grads, slots, wds, lrs):
                nv, ns = self._apply(v, g.astype(v.dtype), s, lr=lr * plr, t=t, wd=wd)
                outs.append(nv)
                outslots.append(ns)
            return outs, outslots

        return update

    def clear_grad(self, set_to_zero=True):
        for p in (self._parameter_list or []):
            p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in (self._parameter_list or [])]

    # ---- state dict ----
    def state_dict(self):
        sd = {"step_count": self._step_count, "accumulators": {}}
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                acc = self._accumulators.get(id(p))
                if acc:
                    sd["accumulators"][p.name or str(i)] = {
                        k: np.asarray(v) for k, v in acc.items()}
        if hasattr(self._learning_rate, "state_dict"):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._step_count = state_dict.get("step_count", 0)
        accs = state_dict.get("accumulators", {})
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                key = p.name or str(i)
                if key in accs:
                    self._accumulators[id(p)] = {
                        k: jnp.asarray(v) for k, v in accs[key].items()}
        if "LR_Scheduler" in state_dict and hasattr(self._learning_rate, "set_state_dict"):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])

    # functional API for the jitted train-step builder (paddle_tpu.jit)
    def init_state(self, params):
        return [self._create_slots(p) for p in params]

    def functional_update(self, values, grads, slots, lr, t, params_meta=None,
                          grad_clip="default"):
        """Pure update over value arrays.

        `params_meta` supplies the parameter OBJECTS the values belong to, so
        per-param coefficients (weight decay, need_clip, lr scale) align with
        them — required whenever `values` is not the optimizer's full
        `_parameter_list` (e.g. one pipeline stage's slice). `grad_clip=None`
        disables in-update clipping for callers that pre-clip globally.
        """
        params = list(params_meta) if params_meta is not None \
            else (self._parameter_list or [])
        if params and len(params) != len(values):
            from ..core.enforce import InvalidArgumentError
            raise InvalidArgumentError(
                f"functional_update: {len(values)} values but {len(params)} "
                "params\n  [Hint] pass params_meta matching the values")
        wds = tuple(self._param_wd(p) for p in params) if params else (self._weight_decay,) * len(values)
        need_clip = tuple(getattr(p, "need_clip", True) for p in params) or (True,) * len(values)
        clip = self._grad_clip if grad_clip == "default" else grad_clip
        fn = self._make_update(clip, wds, need_clip,
                               tuple(getattr(p, "optimize_attr", {}).get("learning_rate", 1.0) for p in params)
                               or (1.0,) * len(values))
        return fn(values, grads, slots, lr, t)


def _clip_fn(clip, grads, need_clip):
    if clip is None:
        return grads
    if isinstance(clip, ClipGradByValue):
        return [jnp.clip(g, clip.min, clip.max) if nc else g
                for g, nc in zip(grads, need_clip)]
    if isinstance(clip, ClipGradByNorm):
        out = []
        for g, nc in zip(grads, need_clip):
            if not nc:
                out.append(g)
                continue
            n = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
            out.append(g * jnp.minimum(clip.clip_norm / jnp.maximum(n, 1e-12), 1.0).astype(g.dtype))
        return out
    if isinstance(clip, ClipGradByGlobalNorm):
        sq = [jnp.sum(g.astype(jnp.float32) ** 2) for g, nc in zip(grads, need_clip) if nc]
        if not sq:
            return grads
        gn = jnp.sqrt(sum(sq))
        factor = clip.clip_norm / jnp.maximum(gn, clip.clip_norm)
        return [g * factor.astype(g.dtype) if nc else g for g, nc in zip(grads, need_clip)]
    return grads


class SGD(Optimizer):
    def _apply(self, p, g, slots, *, lr, t, wd):
        if wd:
            g = g + wd * p
        return p - lr.astype(p.dtype) * g, slots

    def _apply_sparse(self, p, sr, slots, *, lr, t, wd):
        # true sparse rule (sgd_op.h SelectedRows path): touch only the
        # gradient's rows; wd applies to touched rows only
        vals = sr.values.astype(p.dtype)
        if wd:
            vals = vals + wd * p[sr.rows]
        return p.at[sr.rows].add(-lr.astype(p.dtype) * vals), slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_slots(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _apply(self, p, g, slots, *, lr, t, wd):
        if wd:
            g = g + wd * p
        v = self._momentum * slots["velocity"] + g
        if self._use_nesterov:
            p_new = p - lr.astype(p.dtype) * (g + self._momentum * v)
        else:
            p_new = p - lr.astype(p.dtype) * v
        return p_new, {"velocity": v}
