"""paddle.optimizer parity namespace."""
from . import lr  # noqa: F401
from .optimizer import Optimizer, SGD, Momentum  # noqa: F401
from .adam import (  # noqa: F401
    Adadelta, Adagrad, Adam, AdamW, Adamax, Lamb, LarsMomentum, RMSProp,
)


class L2Decay:
    """paddle.regularizer.L2Decay parity (coefficient consumed by optimizers)."""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
