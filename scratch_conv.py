"""Micro-bench: conv layout NCHW vs NHWC on representative ResNet-50 shapes."""
import time, statistics, sys
import numpy as np
import jax, jax.numpy as jnp

PEAK = 1.97e14
B = 128
# (cin, cout, hw, k, stride) representative ResNet-50 convs
SHAPES = [
    (3, 64, 224, 7, 2),     # stem
    (64, 64, 56, 1, 1),
    (64, 64, 56, 3, 1),
    (128, 128, 28, 3, 1),
    (256, 256, 14, 3, 1),
    (512, 512, 7, 3, 1),
    (1024, 256, 14, 1, 1),
]

def bench(cin, cout, hw, k, s, layout):
    if layout == "NCHW":
        x = jnp.zeros((B, cin, hw, hw), jnp.bfloat16)
        dn = ("NCHW", "OIHW", "NCHW")
        w = jnp.zeros((cout, cin, k, k), jnp.bfloat16)
    else:
        x = jnp.zeros((B, hw, hw, cin), jnp.bfloat16)
        dn = ("NHWC", "HWIO", "NHWC")
        w = jnp.zeros((k, k, cin, cout), jnp.bfloat16)
    pad = "SAME"
    @jax.jit
    def f(x, w):
        def body(c, _):
            o = jax.lax.conv_general_dilated(x, w, (s, s), pad, dimension_numbers=dn)
            return c + o.reshape(-1)[0].astype(jnp.float32), None
        c, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), None, length=20)
        return c
    r = f(x, w); r.block_until_ready()
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); float(np.asarray(f(x, w))); ts.append(time.perf_counter() - t0)
    dt = statistics.median(ts) / 20
    out_hw = hw // s
    flops = 2 * B * out_hw * out_hw * cout * cin * k * k
    return dt * 1e3, flops / dt / PEAK

for cin, cout, hw, k, s in SHAPES:
    r = {}
    for layout in ("NCHW", "NHWC"):
        ms, mfu = bench(cin, cout, hw, k, s, layout)
        r[layout] = (ms, mfu)
    print(f"c{cin:4d}->{cout:4d} hw{hw:3d} k{k} s{s}: "
          f"NCHW {r['NCHW'][0]:7.2f}ms mfu={r['NCHW'][1]:.3f} | "
          f"NHWC {r['NHWC'][0]:7.2f}ms mfu={r['NHWC'][1]:.3f}", flush=True)
