import time, statistics
import numpy as np
import jax, jax.numpy as jnp
PEAK = 1.97e14; B = 128; N = 2000
RTT_EST = None

def bench(make_body, n=N):
    @jax.jit
    def f(args):
        def body(c, _):
            o = make_body(args, c)
            return jnp.sum(o).astype(jnp.float32) * 1e-20, None
        return jax.lax.scan(body, jnp.zeros((), jnp.float32), None, length=n)[0]
    return f

def run(f, args, n=N):
    r = f(args); float(np.asarray(r))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); float(np.asarray(f(args))); ts.append(time.perf_counter() - t0)
    return statistics.median(ts)

rng = np.random.RandomState(0)

# measure RTT: empty scan
f0 = bench(lambda a, c: a * (1 + c).astype(a.dtype), n=1)
rtt = run(f0, jnp.zeros((1,), jnp.bfloat16), n=1)
print(f"RTT (empty dispatch+sync): {rtt*1e3:.1f} ms", flush=True)

def conv_case(name, xshape, wshape, stride, pad, dn, flops):
    x = jnp.asarray(rng.rand(*xshape).astype(np.float32) * 0.1).astype(jnp.bfloat16)
    w = jnp.asarray(rng.rand(*wshape).astype(np.float32) * 0.1).astype(jnp.bfloat16)
    f = bench(lambda a, c: jax.lax.conv_general_dilated(
        a[0], a[1] * (1 + c).astype(a[1].dtype), stride, pad, dimension_numbers=dn))
    tot = run(f, (x, w))
    dt = (tot - rtt) / N
    print(f"{name}: {dt*1e3:.4f} ms  mfu={flops/dt/PEAK:.3f}", flush=True)

conv_case("stem 7x7s2", (B,3,224,224), (64,3,7,7), (2,2), [(3,3),(3,3)], ("NCHW","OIHW","NCHW"),
          2*B*112*112*64*3*49)
conv_case("s2d 4x4s1 ", (B,12,112,112), (64,12,4,4), (1,1), [(2,1),(2,1)], ("NCHW","OIHW","NCHW"),
          2*B*112*112*64*12*16)
conv_case("3x3 c64 hw56 ", (B,64,56,56), (64,64,3,3), (1,1), [(1,1),(1,1)], ("NCHW","OIHW","NCHW"),
          2*B*56*56*64*64*9)
conv_case("3x3 c256 hw14", (B,256,14,14), (256,256,3,3), (1,1), [(1,1),(1,1)], ("NCHW","OIHW","NCHW"),
          2*B*14*14*256*256*9)
conv_case("1x1 c64->64 hw56  ", (B,64,56,56), (64,64,1,1), (1,1), [(0,0),(0,0)], ("NCHW","OIHW","NCHW"),
          2*B*56*56*64*64)
conv_case("1x1 c256->64 hw56 ", (B,256,56,56), (64,256,1,1), (1,1), [(0,0),(0,0)], ("NCHW","OIHW","NCHW"),
          2*B*56*56*256*64)
conv_case("1x1 c1024->256 h14", (B,1024,14,14), (256,1024,1,1), (1,1), [(0,0),(0,0)], ("NCHW","OIHW","NCHW"),
          2*B*14*14*1024*256)

# maxpool NCHW vs NHWC (input-add carry; subtract BW cost mentally)
for name, shape, wdims, sdims, pdims in (
        ("maxpool NCHW", (B,64,112,112), (1,1,3,3), (1,1,2,2), [(0,0),(0,0),(1,1),(1,1)]),
        ("maxpool NHWC", (B,112,112,64), (1,3,3,1), (1,2,2,1), [(0,0),(1,1),(1,1),(0,0)])):
    x = jnp.asarray(rng.rand(*shape).astype(np.float32)).astype(jnp.bfloat16)
    f = bench(lambda a, c, wd=wdims, sd=sdims, pd=pdims: jax.lax.reduce_window(
        a * (1 + c).astype(a.dtype), jnp.bfloat16(-1e30), jax.lax.max, wd, sd, pd), n=500)
    tot = run(f, x, n=500)
    dt = (tot - rtt) / 500
    print(f"{name}: {dt*1e3:.4f} ms", flush=True)
