import os
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np, sys
sys.path.insert(0, ".")
import jax
jax.config.update("jax_platforms", "cpu")
import paddle_tpu as paddle
from paddle_tpu import models

paddle.seed(0)
a = models.resnet18(num_classes=8)
paddle.seed(0)
b = models.resnet18(num_classes=8, data_format="NHWC")
b.set_state_dict(a.state_dict())
a.eval(); b.eval()
x = np.random.rand(2, 3, 64, 64).astype("float32")
ya = a(paddle.to_tensor(x)).numpy()
yb = b(paddle.to_tensor(x.transpose(0, 2, 3, 1))).numpy()
print("max diff:", np.abs(ya - yb).max())
assert np.abs(ya - yb).max() < 2e-4, "NHWC mismatch"
print("NHWC OK")
