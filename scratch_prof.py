"""Scratch: dissect ResNet-50 inference perf on the real chip."""
import time, statistics, sys
import numpy as np
import jax, jax.numpy as jnp

sys.path.insert(0, ".")
import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.jit.functional import functional_call, split_state

PEAK = 1.97e14
FLOPS_IMG = 4.1e9

paddle.seed(0)
net = models.resnet50()
net.eval()
trainable, frozen = split_state(net)
pnames, bnames = list(trainable), list(frozen)
params = [trainable[n]._value for n in pnames]
buffers = [frozen[n]._value for n in bnames]
print(f"n params={len(params)} n buffers={len(buffers)}")

def make_fn(dtype):
    p = [a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a for a in params]
    b = [a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a for a in buffers]
    @jax.jit
    def f(x):
        out = functional_call(net, pnames, p, bnames, b, paddle.Tensor(x))
        return out._value if hasattr(out, "_value") else out
    return f

def timeit(f, x, n=30, reps=3):
    r = f(x); r.block_until_ready(); float(np.asarray(r.reshape(-1)[0]))
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(n):
            r = f(x)
        float(np.asarray(r.reshape(-1)[0]))
        dt = time.perf_counter() - t0
        rates.append(x.shape[0] * n / dt)
    med = statistics.median(rates)
    return med, (max(rates) - min(rates)) / med

import argparse
ap = argparse.ArgumentParser()
ap.add_argument("--dtype", default="bfloat16")
ap.add_argument("--batch", type=int, nargs="+", default=[32])
ap.add_argument("--scan", action="store_true")
args = ap.parse_args()
dtype = getattr(jnp, args.dtype)
f = make_fn(dtype)
for bs in args.batch:
    x = jnp.asarray(np.random.rand(bs, 3, 224, 224).astype(np.float32)).astype(dtype)
    med, spread = timeit(f, x)
    print(f"dtype={dtype.__name__} batch={bs}: {med:.0f} img/s  mfu={med*FLOPS_IMG/PEAK:.3f} spread={spread:.3f}", flush=True)

# scan-based: one dispatch per span -> pure device throughput
def make_scan_fn(dtype, n_inner=30):
    p = [a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a for a in params]
    b = [a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a for a in buffers]
    @jax.jit
    def f(x):
        def body(carry, _):
            out = functional_call(net, pnames, p, bnames, b, paddle.Tensor(x + carry))
            o = out._value if hasattr(out, "_value") else out
            return o.reshape(-1)[0].astype(x.dtype) * 0, None
        c, _ = jax.lax.scan(body, jnp.zeros((), x.dtype), None, length=n_inner)
        return c
    return f

if getattr(args, "scan", None):
    n_inner = 30
    f = make_scan_fn(dtype, n_inner)
    for bs in args.batch:
        x = jnp.asarray(np.random.rand(bs, 3, 224, 224).astype(np.float32)).astype(dtype)
        r = f(x); r.block_until_ready()
        rates = []
        for _ in range(3):
            t0 = time.perf_counter()
            r = f(x); float(np.asarray(r))
            rates.append(bs * n_inner / (time.perf_counter() - t0))
        med = statistics.median(rates)
        spr = (max(rates) - min(rates)) / med
        print(f"SCAN dtype={dtype.__name__} batch={bs}: {med:.0f} img/s  mfu={med*FLOPS_IMG/PEAK:.3f} spread={spr:.3f}", flush=True)
