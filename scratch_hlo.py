import sys
import numpy as np
import jax, jax.numpy as jnp
sys.path.insert(0, ".")
import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.jit.functional import functional_call, split_state

paddle.seed(0)
net = models.resnet50(data_format="NHWC"); net.eval()
trainable, frozen = split_state(net)
pnames, bnames = list(trainable), list(frozen)
dtype = jnp.bfloat16
p = [trainable[n]._value.astype(dtype) if jnp.issubdtype(trainable[n]._value.dtype, jnp.floating) else trainable[n]._value for n in pnames]
b = [frozen[n]._value.astype(dtype) if jnp.issubdtype(frozen[n]._value.dtype, jnp.floating) else frozen[n]._value for n in bnames]

def f(x):
    out = functional_call(net, pnames, p, bnames, b, paddle.Tensor(x))
    return out._value if hasattr(out, "_value") else out

x = jnp.zeros((128, 224, 224, 3), dtype)
lowered = jax.jit(f).lower(x)
comp = lowered.compile()
hlo = comp.as_text()
open("/root/repo/_trace/opt.hlo", "w").write(hlo)
import re
# print the definition line of the hot fusions
for name in ["fusion", "fusion.1 ", "fusion.2 ", "fusion.16", "fusion.14", "fusion.39", "fusion.6 ", "fusion.4 ", "fusion.3 ", "fusion.5 ", "copy-done", "copy.1"]:
    for line in hlo.splitlines():
        ls = line.strip()
        if ls.startswith(f"%{name.strip()} =") or ls.startswith(f"{name.strip()} ="):
            print(line.strip()[:240]); break
ca = comp.cost_analysis()
ca = ca[0] if isinstance(ca, list) else ca
print("XLA flops per step:", ca.get("flops"), "-> per img:", ca.get("flops", 0) / 128 / 1e9, "GFLOP")
print("bytes accessed:", ca.get("bytes accessed", 0) / 1e9, "GB")
