"""Flagship benchmark: ERNIE/BERT-base pretraining-style train step on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no in-repo numbers (BASELINE.md) — vs_baseline
compares against the recorded best from previous rounds when present
(bench_baseline.json), else 1.0.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def main():
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import models
    from paddle_tpu.jit import TrainStep

    backend = jax.default_backend()
    batch, seqlen = (32, 128) if backend == "tpu" else (8, 64)

    paddle.seed(0)
    base = models.ernie_base(hidden_dropout_prob=0.0) if backend == "tpu" else \
        models.ErnieModel(vocab_size=1024, hidden_size=128, num_hidden_layers=2,
                          num_attention_heads=4, intermediate_size=512,
                          hidden_dropout_prob=0.0)
    net = models.ErnieForPretraining(base)
    ce = nn.CrossEntropyLoss()

    def loss_fn(logits, nsp_logits, ids, nsp):
        v = logits.shape[-1]
        return ce(logits.reshape([-1, v]), ids.reshape([-1])) + ce(nsp_logits, nsp)

    opt = paddle.optimizer.AdamW(parameters=net.parameters(), learning_rate=1e-4)
    step = TrainStep(net, loss_fn, opt, amp_dtype="bfloat16", n_model_inputs=1)

    vocab = base.embeddings.word_embeddings.weight.shape[0]
    ids = paddle.to_tensor(np.random.randint(0, vocab, (batch, seqlen)).astype(np.int32))
    nsp = paddle.to_tensor(np.random.randint(0, 2, (batch,)).astype(np.int32))

    # warmup / compile (sync via host transfer: on the axon tunnel
    # block_until_ready returns early, so D2H is the only true barrier)
    loss = step(ids, ids, nsp)
    float(loss.numpy())

    n_steps = 20 if backend == "tpu" else 5
    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = step(ids, ids, nsp)
    float(loss.numpy())
    dt = time.perf_counter() - t0

    sps = batch * n_steps / dt
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "bench_baseline.json")
    vs = 1.0
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as f:
                ref = json.load(f).get("value")
            if ref:
                vs = sps / ref
        except Exception:
            pass
    print(json.dumps({
        "metric": f"ernie_base_train_samples_per_sec_per_chip[{backend},b{batch},s{seqlen},bf16]",
        "value": round(sps, 2),
        "unit": "samples/s",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
