"""Benchmark harness over the BASELINE.md workload set.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
The primary metric stays the flagship ERNIE/BERT-base train step (median of
R reps, spread reported); "extra" carries the other BASELINE.md workloads —
ResNet-50 inference imgs/s through the Predictor, LeNet imperative dispatch
latency, and a seq-4096 attention config that exercises the Pallas flash
kernel fwd+bwd against the fused-XLA path — each with an approximate MFU
against the chip's bf16 peak.

The reference publishes no in-repo numbers (BASELINE.md); vs_baseline
compares against the recorded best from previous rounds (bench_baseline.json).
Reference bench patterns: tools/ci_model_benchmark.sh:47 (model CI),
paddle/fluid/operators/benchmark/op_tester.cc:1 (op microbench).
"""
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

# v5e bf16 peak FLOPs/s (scaling-book figure); used only for the MFU estimate
PEAK_FLOPS = 1.97e14


def _sync(x):
    # On the axon tunnel block_until_ready can return early; a D2H copy is
    # the reliable barrier. Keep it OUTSIDE timed loops; each timed region
    # ends with exactly one sync.
    return float(np.asarray(x.reshape(-1)[0]))


def _median_rate(run_once, n_steps, reps, payload_per_step):
    """run_once(n) executes n steps and returns a device value to sync on.
    Returns (median rate, spread) in payload units/sec over `reps` trials."""
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run_once(n_steps)
        _sync(out)
        dt = time.perf_counter() - t0
        rates.append(payload_per_step * n_steps / dt)
    med = statistics.median(rates)
    spread = (max(rates) - min(rates)) / med if med else 0.0
    return med, spread


def _timeline_breakdown(step, batch_tensors, n_steps):
    """Per-phase step-time attribution via the obs plane: run a few
    per-step (__call__) iterations with FLAGS_obs_timeline on, aggregate
    the steady-state records, and return
    (phases_ms, wall_ms, coverage, cost) where coverage = phase-sum/wall
    (the ≈1.0 invariant the obs tests enforce) and cost is the
    compiler-attributed {flops, bytes_accessed} of the step executable."""
    import paddle_tpu as paddle
    from paddle_tpu import obs

    paddle.set_flags({"FLAGS_obs_timeline": True})
    obs.reset()
    try:
        for _ in range(n_steps + 1):   # +1: the per-step signature compiles
            _sync(step(*batch_tensors)._value)
        recs = [r for r in obs.timeline().records()
                if "trace_compile" not in r.get("phases", {})
                and "build" not in r.get("phases", {})]
        cost = step.cost_analysis(*batch_tensors)
    finally:
        paddle.set_flags({"FLAGS_obs_timeline": False})
    if not recs:
        return {}, 0.0, 0.0, cost
    agg = {}
    for r in recs:
        for k, v in r["phases"].items():
            agg[k] = agg.get(k, 0.0) + v
    n = len(recs)
    phases_ms = {k: round(v / n * 1e3, 3) for k, v in agg.items()}
    wall_ms = sum(r["wall"] for r in recs) / n * 1e3
    coverage = (sum(agg.values()) / n * 1e3) / wall_ms if wall_ms else 0.0
    return phases_ms, round(wall_ms, 3), round(coverage, 3), cost


def _memory_breakdown(step, batch_tensors):
    """HBM attribution for the workload (obs/memory.py): run one tagged
    step with FLAGS_mem_census on, then report peak live bytes, the
    census' per-tag shares, and the step executable's compiler-reported
    argument/output/temp breakdown."""
    import paddle_tpu as paddle
    from paddle_tpu.obs import memory as _memory

    paddle.set_flags({"FLAGS_mem_census": True})
    try:
        _sync(step(*batch_tensors)._value)   # one step with tagging live
        rec = _memory.census(publish=False, store=False)
        total = int(rec.get("total_bytes", 0))
        shares = {name: round(b["bytes"] / total, 4)
                  for name, b in sorted(rec.get("tags", {}).items())} \
            if total else {}
        try:
            report = step.memory_report(*batch_tensors)
        except Exception:
            report = {}
        peaks = _memory.phase_peaks()
        return {"live_bytes": total,
                "peak_bytes": max([total] + list(peaks.values())),
                "tag_shares": shares,
                "executables": {"train_step": report}}
    finally:
        paddle.set_flags({"FLAGS_mem_census": False})
        _memory.reset()


def _overlap_ab(step, batch_np, n_steps, depth=2):
    """Prefetch on/off A/B on the per-step path: same host batches, same
    step executable — measure samples/s and the per-phase time both ways.
    The win to look for: the data_wait+h2d share of total wall collapses
    when the feeder thread hides them under the previous step (they
    reappear as hidden `prefetch_h2d` in the between bucket). Knob:
    BENCH_PREFETCH=ab|on|off (default ab runs both arms)."""
    import paddle_tpu as paddle
    from paddle_tpu import obs
    from paddle_tpu.io.prefetch import DevicePrefetcher

    arm = os.environ.get("BENCH_PREFETCH", "ab").lower()
    arms = {"ab": ("prefetch_off", "prefetch_on"),
            "on": ("prefetch_on",), "off": ("prefetch_off",)}.get(arm)
    if arms is None:
        arms = ("prefetch_off", "prefetch_on")
    batch_size = batch_np[0].shape[0]
    out = {}
    for mode in arms:
        src = [tuple(a.copy() for a in batch_np) for _ in range(n_steps)]
        feed = src if mode == "prefetch_off" \
            else DevicePrefetcher(src, depth=depth)
        paddle.set_flags({"FLAGS_obs_timeline": True})
        obs.reset()
        try:
            t0 = time.perf_counter()
            loss = None
            for b in feed:
                loss = step(*b)
            _sync(loss._value)
            dt = time.perf_counter() - t0
            recs = [r for r in obs.timeline().records()
                    if "trace_compile" not in r.get("phases", {})
                    and "build" not in r.get("phases", {})]
        finally:
            paddle.set_flags({"FLAGS_obs_timeline": False})
            if feed is not src:
                feed.close()
        agg, between = {}, {}
        for r in recs:
            for k, v in r.get("phases", {}).items():
                agg[k] = agg.get(k, 0.0) + v
            for k, v in r.get("between", {}).items():
                between[k] = between.get(k, 0.0) + v
        n = max(len(recs), 1)
        wall = sum(r["wall"] for r in recs)
        total = wall + sum(between.values()) or 1e-9
        # visible input-feed cost: in-step h2d + consumer stalls between
        # steps; the hidden feeder-thread prefetch_h2d is NOT charged here
        # (it overlapped compute) but stays reported for the books
        feed_share = (agg.get("h2d", 0.0) + agg.get("data_wait", 0.0)
                      + between.get("data_wait", 0.0)
                      + between.get("h2d", 0.0)) / total
        out[mode] = {
            "samples_per_sec": round(batch_size * n_steps / dt, 2),
            "phases_ms": {k: round(v / n * 1e3, 3)
                          for k, v in sorted(agg.items())},
            "between_ms": {k: round(v / n * 1e3, 3)
                           for k, v in sorted(between.items())},
            "data_wait_h2d_share": round(feed_share, 4),
        }
    if len(arms) == 2:
        out["share_delta"] = round(
            out["prefetch_off"]["data_wait_h2d_share"]
            - out["prefetch_on"]["data_wait_h2d_share"], 4)
        off_sps = out["prefetch_off"]["samples_per_sec"]
        if off_sps:
            out["speedup"] = round(
                out["prefetch_on"]["samples_per_sec"] / off_sps, 3)
    return out


def bench_ernie_train(backend):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import models
    from paddle_tpu.jit import TrainStep

    batch, seqlen = (32, 128) if backend == "tpu" else (8, 64)
    paddle.seed(0)
    base = models.ernie_base(hidden_dropout_prob=0.0) if backend == "tpu" else \
        models.ErnieModel(vocab_size=1024, hidden_size=128, num_hidden_layers=2,
                          num_attention_heads=4, intermediate_size=512,
                          hidden_dropout_prob=0.0)
    net = models.ErnieForPretraining(base)
    ce = nn.CrossEntropyLoss()

    def loss_fn(logits, nsp_logits, ids, nsp):
        v = logits.shape[-1]
        return ce(logits.reshape([-1, v]), ids.reshape([-1])) + ce(nsp_logits, nsp)

    opt = paddle.optimizer.AdamW(parameters=net.parameters(), learning_rate=1e-4)
    step = TrainStep(net, loss_fn, opt, amp_dtype="bfloat16", n_model_inputs=1)

    vocab = base.embeddings.word_embeddings.weight.shape[0]
    n_steps, reps = (100, 5) if backend == "tpu" else (5, 2)
    # Device-side training loop (TrainStep.run = lax.scan over steps): one
    # dispatch + one sync per span, mirroring the reference's C++ trainer
    # hot loop (trainer.h:59) that likewise never returns to the host
    # between steps. Batches are stacked [n_steps, ...] on device up front.
    ids_all = paddle.to_tensor(
        np.random.randint(0, vocab, (n_steps, batch, seqlen)).astype(np.int32))
    nsp_all = paddle.to_tensor(
        np.random.randint(0, 2, (n_steps, batch)).astype(np.int32))

    def run(n):
        assert n == n_steps, "span length is fixed by the stacked batch"
        losses = step.run(ids_all, ids_all, nsp_all)
        return losses._value

    _sync(run(n_steps))  # compile + warmup (one full span)
    sps, spread = _median_rate(run, n_steps, reps, batch)

    # per-phase attribution of the step + compiler-attributed MFU: where
    # the ROADMAP "MFU 0.51 -> 0.65+" gap actually sits (input feed vs
    # compile vs compute vs optimizer), measured on the per-step path
    ids0, nsp0 = ids_all[0], nsp_all[0]
    tl_ms, tl_wall_ms, tl_cov, cost = _timeline_breakdown(
        step, (ids0, ids0, nsp0), 5 if backend == "tpu" else 2)

    # prefetch on/off A/B: per-optimisation attribution of the win — the
    # data_wait/h2d phase share before vs after async device prefetch, on
    # the same step executable (BENCH_r06 records this next to the
    # headline samples/s)
    ids_np = np.asarray(ids0._value)
    nsp_np = np.asarray(nsp0._value)
    overlap = _overlap_ab(step, (ids_np, ids_np, nsp_np),
                          20 if backend == "tpu" else 3)

    # HBM attribution: who owns the live bytes (params/slots/activations/
    # ...), plus XLA's argument/output/temp breakdown for the step
    memory = _memory_breakdown(step, (ids0, ids0, nsp0))

    # train matmul FLOPs/sample ~= 6*N_matmul*S + 3*L*4*S^2*H (PaLM-style)
    # + the weight-tied MLM head (6*S*H*V: its [V,H] weight is the embedding
    # table, excluded from n_matmul, but its 3 matmuls are ~25% of the work)
    h = base.embeddings.word_embeddings.weight.shape[1]
    nlayers = len(base.layers)
    n_matmul = sum(int(np.prod(p.shape)) for p in net.parameters()
                   if len(p.shape) == 2 and p.shape[0] != vocab)
    flops_sample = (6 * n_matmul * seqlen + 3 * nlayers * 4 * seqlen ** 2 * h
                    + 6 * seqlen * h * vocab)
    mfu = sps * flops_sample / PEAK_FLOPS if backend == "tpu" else 0.0
    # attributed MFU: XLA's own FLOP count for the step executable over the
    # measured rate — no hand-derived formula in the loop
    mfu_attr = 0.0
    if cost.get("flops") and backend == "tpu":
        mfu_attr = cost["flops"] * (sps / batch) / PEAK_FLOPS
    return {"samples_per_sec": round(sps, 2), "spread": round(spread, 3),
            "mfu": round(mfu, 4), "mfu_attributed": round(mfu_attr, 4),
            "flops_per_step_attributed": cost.get("flops"),
            "bytes_per_step_attributed": cost.get("bytes_accessed"),
            "timeline_ms": tl_ms, "timeline_wall_ms": tl_wall_ms,
            "timeline_phase_coverage": tl_cov,
            "overlap": overlap,
            "memory": memory,
            "batch": batch, "seqlen": seqlen,
            "attention": "XLA fused (measured r5: forcing the Pallas flash "
                         "kernel into this s128 training path loses 14% — "
                         "999.1 vs 1159.9 samples/s — the tiny 128x128 "
                         "score tiles can't amortize kernel-call+softmax "
                         "overhead that XLA fuses into the batched matmul; "
                         "the 1024+ crossover in nn/functional/attention.py "
                         "stands)"}


def _predictor_rate(net, in_shape, n_steps, reps, precision=None):
    """Shared deploy-bench scaffold: jit.save -> Config -> Predictor ->
    feed once -> time n_steps-run spans syncing on ONE element of the
    first output (device_value; a full copy_to_cpu of a big head would
    dwarf the timed region on the tunnel). Returns (imgs_per_sec, spread).
    """
    import tempfile
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.jit import InputSpec, save

    net.eval()
    batch = in_shape[0]
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "model")
        save(net, path, input_spec=[InputSpec(list(in_shape), "float32")],
             precision=precision)
        cfg = Config(path)
        cfg.enable_tpu()
        pred = create_predictor(cfg)
        x = np.random.rand(*in_shape).astype("float32")
        pred.get_input_handle(pred.get_input_names()[0]).copy_from_cpu(x)
        pred.run()
        out_h = pred.get_output_handle(pred.get_output_names()[0])
        out_h.copy_to_cpu()  # warmup incl. one full host readback

        def run_once(n):
            for _ in range(n):
                pred.run()
            return out_h.device_value()

        _sync(run_once(n_steps))  # full-span warmup before timed reps
        return _median_rate(run_once, n_steps, reps, batch)


def bench_resnet50_infer(backend):
    """ResNet-50 through the inference Predictor.

    TPU-shaped deploy config: NHWC layout (channels on the lane dim — the
    NCHW maxpool alone costs 1.1ms vs 0.30ms NHWC at this batch), bf16
    export precision (MXU path), batch 128, and long timed spans so the
    ~0.1s tunnel dispatch+sync RTT stays <5% of each measurement.
    """
    import paddle_tpu as paddle
    from paddle_tpu import models

    paddle.seed(0)
    if backend == "tpu":
        batch = 128
        net = models.resnet50(data_format="NHWC")
        med, spread = _predictor_rate(net, (batch, 224, 224, 3), 250, 5,
                                      precision="bfloat16")
    else:
        batch = 2
        net = models.LeNet(num_classes=10)
        med, spread = _predictor_rate(net, (batch, 1, 28, 28), 3, 2)
    # 7.913 GFLOP/img from XLA cost_analysis on this exact compiled model
    # (2 flops per MAC, the PaLM-MFU convention the ERNIE bench also uses;
    # He et al.'s "4.1 GFLOPs" counts multiply-ADDS). At batch 128 the
    # compiled step moves 7.06 GB — it runs at ~96% of the 820 GB/s HBM
    # roofline, so imgs/s, not MFU, is the binding metric.
    flops_img = 7.913e9 if backend == "tpu" else 0.0
    mfu = med * flops_img / PEAK_FLOPS
    out = {"imgs_per_sec": round(med, 2), "spread": round(spread, 3),
           "mfu": round(mfu, 4), "batch": batch}
    if backend == "tpu":
        out.update(layout="NHWC", precision="bf16", hbm_roofline_frac=0.96)
    return out


def bench_resnet50_infer_int8(backend):
    """Weight-only int8 ResNet-50 through the Predictor: int8 params live
    in HBM, per-channel dequant to bf16 fuses into each conv (export-time
    quantization; mkldnn_quantizer/TRT-int8 role)."""
    import paddle_tpu as paddle
    from paddle_tpu import models

    if backend != "tpu":
        return {"skipped": "needs real chip"}
    batch = 128
    paddle.seed(0)
    net = models.resnet50(data_format="NHWC")
    med, spread = _predictor_rate(net, (batch, 224, 224, 3), 200, 3,
                                  precision="int8")
    return {"imgs_per_sec": round(med, 2), "spread": round(spread, 3),
            "batch": batch, "precision": "int8-weight-only"}


def bench_lenet_dispatch(backend):
    """Imperative (eager, per-op dispatch) fwd+bwd+step latency — the
    reference dygraph hot loop (SURVEY §3.2)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import models

    paddle.seed(0)
    net = models.LeNet(num_classes=10)
    opt = paddle.optimizer.SGD(parameters=net.parameters(), learning_rate=0.01)
    ce = nn.CrossEntropyLoss()
    x = paddle.to_tensor(np.random.rand(32, 1, 28, 28).astype("float32"))
    y = paddle.to_tensor(np.random.randint(0, 10, (32,)))

    def one():
        loss = ce(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    for _ in range(6):   # warmup past the step-chain capture threshold
        loss = one()
    _sync(loss._value)
    n = 20 if backend == "tpu" else 5
    rates = []
    for _ in range(7 if backend == "tpu" else 2):
        t0 = time.perf_counter()
        for _ in range(n):
            loss = one()
        _sync(loss._value)
        rates.append((time.perf_counter() - t0) / n * 1000)
    ms = statistics.median(rates)
    return {"step_latency_ms": round(ms, 2),
            "note": "imperative hot loop with r5 step-chain capture: a "
                    "top-level Layer repeatedly called with one signature "
                    "is promoted to its captured static program "
                    "(FLAGS_eager_auto_jit, nn/layer/layers.py), and the "
                    "tape walk replays as ONE jitted executable keyed on "
                    "tape structure (core/autograd.py _fused_backward) — "
                    "fwd 1 + bwd 1 + fused optimizer 1 dispatch instead "
                    "of one per op (150.7 ms in r4)",
            "lazy": _lenet_lazy_ab(backend)}


def _lenet_lazy_ab(backend):
    """FLAGS_lazy_eager on/off A/B on the uncaptured eager hot loop
    (step-chain capture disabled in BOTH arms so the per-op dispatch tax
    is actually on the table). Per arm: step latency plus the segment
    count and signature-cache hit rate from the monitor counters. Knob:
    BENCH_LAZY=ab|on|off (default ab runs both arms)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import models, monitor

    arm = os.environ.get("BENCH_LAZY", "ab").lower()
    arms = {"ab": ("lazy_off", "lazy_on"), "on": ("lazy_on",),
            "off": ("lazy_off",)}.get(arm)
    if arms is None:
        arms = ("lazy_off", "lazy_on")
    n = 20 if backend == "tpu" else 5
    reps = 7 if backend == "tpu" else 2
    out = {}
    for mode in arms:
        paddle.seed(0)
        net = models.LeNet(num_classes=10)
        opt = paddle.optimizer.SGD(parameters=net.parameters(),
                                   learning_rate=0.01)
        ce = nn.CrossEntropyLoss()
        x = paddle.to_tensor(np.random.rand(32, 1, 28, 28).astype("float32"))
        y = paddle.to_tensor(np.random.randint(0, 10, (32,)))

        def one():
            loss = ce(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        paddle.set_flags({"FLAGS_lazy_eager": mode == "lazy_on",
                          "FLAGS_eager_auto_jit": False,
                          "FLAGS_monitor": True})
        try:
            for _ in range(6):
                loss = one()
            _sync(loss._value)
            c0 = monitor.snapshot().get("counters", {})
            rates = []
            for _ in range(reps):
                t0 = time.perf_counter()
                for _ in range(n):
                    loss = one()
                _sync(loss._value)
                rates.append((time.perf_counter() - t0) / n * 1000)
            c1 = monitor.snapshot().get("counters", {})
        finally:
            paddle.set_flags({"FLAGS_lazy_eager": False,
                              "FLAGS_eager_auto_jit": True,
                              "FLAGS_monitor": False})

        def delta(k):
            return c1.get(k, 0) - c0.get(k, 0)

        flushes = delta("lazy.flushes")
        out[mode] = {
            "step_latency_ms": round(statistics.median(rates), 2),
            "segments": flushes,
            "cache_hit_rate": round(delta("lazy.cache_hits") / flushes, 4)
            if flushes else 0.0,
            "ops_per_op_dispatches": delta("dispatch.op_count"),
        }
    if len(arms) == 2:
        out["speedup"] = round(
            out["lazy_off"]["step_latency_ms"]
            / max(out["lazy_on"]["step_latency_ms"], 1e-9), 3)
    return out


def bench_warm_start(backend):
    """Persistent compile-cache A/B: the SAME workload process spawned
    twice against one `FLAGS_compile_cache_dir` — arm one starts with the
    directory empty (every signature lowers, traces, compiles, and is
    AOT-serialized to disk), arm two starts warm (every signature
    deserializes a prior process's executable: zero trace_compile). Per
    arm: time-to-first-train-step, time-to-first-inference (serving
    bucket warm-up through the cache), and the compile/hit/miss/store
    counters; plus the cold/warm speedups and a bit-identity check on the
    train + serve output digests. Workload: tests/warm_start_runner.py
    (LeNet TrainStep x2 + to_static predictor bucket warm-up).
    Knob: BENCH_WARMSTART=ab|off (default ab)."""
    import shutil
    import subprocess
    import tempfile

    if os.environ.get("BENCH_WARMSTART", "ab").lower() == "off":
        return {"skipped": "BENCH_WARMSTART=off"}
    here = os.path.dirname(os.path.abspath(__file__))
    runner = os.path.join(here, "tests", "warm_start_runner.py")
    cache_dir = tempfile.mkdtemp(prefix="bench_warmstart_")
    out = {}
    try:
        for arm in ("cold", "warm"):
            t0 = time.perf_counter()
            proc = subprocess.run(
                [sys.executable, runner, cache_dir],
                capture_output=True, text=True, timeout=600,
                env={**os.environ, "JAX_PLATFORMS":
                     "cpu" if backend != "tpu" else "tpu"})
            wall_s = time.perf_counter() - t0
            if proc.returncode != 0 or not proc.stdout.strip():
                return {"error": f"{arm}: rc={proc.returncode}",
                        "stderr_tail": proc.stderr[-400:]}
            r = json.loads(proc.stdout.strip().splitlines()[-1])
            cc = r["compile_cache"]
            out[arm] = {
                "t_first_train_s": round(r["t_first_train_s"], 3),
                "t_first_infer_s": round(r["t_first_infer_s"], 3),
                "process_wall_s": round(wall_s, 3),
                "trace_compile": r["trace_compile"],
                "cache_hits": cc["hits"],
                "cache_misses": cc["misses"],
                "cache_stores": cc["stores"],
                "cache_fallbacks": cc["fallbacks"],
                "_digests": (r["train_digest"], r["serve_digest"]),
            }
        cold, warm = out["cold"], out["warm"]
        out["bit_identical"] = cold.pop("_digests") == warm.pop("_digests")
        out["speedup_first_train"] = round(
            cold["t_first_train_s"] / max(warm["t_first_train_s"], 1e-9), 3)
        out["speedup_first_infer"] = round(
            cold["t_first_infer_s"] / max(warm["t_first_infer_s"], 1e-9), 3)
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return out


def bench_flash_attention(backend):
    """Long-seq attention fwd+bwd: Pallas flash kernel vs fused-XLA path."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.kernels.flash_attention import (_flash_core,
                                                    _reference_bhsd)

    if backend != "tpu":
        return {"skipped": "needs real chip"}
    bh, s, d = 12, 8192, 64  # GPT/ERNIE-base head config at long context
    # bf16 inputs: the training dtype, and what keeps the kernel's dots on
    # the full-rate MXU path
    q = jnp.asarray(np.random.rand(bh, s, d).astype(np.float32) * 0.1).astype(jnp.bfloat16)
    k = jnp.asarray(np.random.rand(bh, s, d).astype(np.float32) * 0.1).astype(jnp.bfloat16)
    v = jnp.asarray(np.random.rand(bh, s, d).astype(np.float32) * 0.1).astype(jnp.bfloat16)

    def make(fn):
        def loss(a, b, c):
            return (fn(a, b, c).astype(jnp.float32) ** 2).sum()
        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        def run(n):
            out = None
            for _ in range(n):
                out = g(q, k, v)
            return out[0]
        return run

    flash = make(lambda a, b, c: _flash_core(a, b, c, True, 512, 512, False))
    # baseline = the FASTER fused-XLA variant at this size: upcasting to
    # f32 before the einsums (21 steps/s) beats native-bf16 dots (2.7 —
    # the autodiff-saved extra bf16 copy of the 3.2GB score matrix thrashes
    # HBM); comparing against the strongest baseline keeps speedup honest
    ref = make(lambda a, b, c: _reference_bhsd(
        a.astype(jnp.float32), b.astype(jnp.float32),
        c.astype(jnp.float32), True).astype(a.dtype))
    results = {}
    # spans long enough that the ~0.1s tunnel sync RTT stays <10% of the
    # timed region (the flash step is ~7.4ms on device)
    for name, run, n in (("flash", flash, 150), ("xla_ref", ref, 60)):
        _sync(run(2))
        rates = []
        for _ in range(3):
            t0 = time.perf_counter()
            _sync(run(n))
            rates.append(n / (time.perf_counter() - t0))
        results[name] = statistics.median(rates)
    # fwd 4*S^2*D matmul flops per bh slice, halved for causal; bwd ~2.5x
    flops_step = 3.5 * 4 * s * s * d * bh * 0.5

    # d128 point: every dot full-rate on the MXU (nominal ceiling 1.0), so
    # kernel-structure headroom is measured honestly, not hidden behind the
    # d64 half-rate handicap. Same total flops (bh halved).
    bh2, d2 = 6, 128
    q2 = jnp.asarray(np.random.rand(bh2, s, d2).astype(np.float32) * 0.1).astype(jnp.bfloat16)
    k2 = jnp.asarray(np.random.rand(bh2, s, d2).astype(np.float32) * 0.1).astype(jnp.bfloat16)
    v2 = jnp.asarray(np.random.rand(bh2, s, d2).astype(np.float32) * 0.1).astype(jnp.bfloat16)

    def loss2(a, b, c):
        return (_flash_core(a, b, c, True, 512, 512, False).astype(jnp.float32) ** 2).sum()
    g2 = jax.jit(jax.grad(loss2, argnums=(0, 1, 2)))

    def run_d128(n):
        out = None
        for _ in range(n):
            out = g2(q2, k2, v2)
        return out[0]
    _sync(run_d128(2))
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        _sync(run_d128(150))
        rates.append(150 / (time.perf_counter() - t0))
    d128_rate = statistics.median(rates)
    flops_d128 = 3.5 * 4 * s * s * d2 * bh2 * 0.5

    return {"flash_steps_per_sec": round(results["flash"], 2),
            "xla_steps_per_sec": round(results["xla_ref"], 2),
            "flash_speedup": round(results["flash"] / results["xla_ref"], 3),
            "flash_mfu": round(results["flash"] * flops_step / PEAK_FLOPS, 4),
            "flash_mfu_d128": round(d128_rate * flops_d128 / PEAK_FLOPS, 4),
            "seq": s,
            # roofline: at head_dim 64 every qk^T/pv/dq dot leaves half the
            # 128-lane MXU contraction/output dim idle, capping the nominal
            # MFU ceiling near 0.5 for this head geometry; d128 runs every
            # dot full-rate (nominal ceiling 1.0). r5 kernels: base-2
            # softmax domain, geometry-picked softmax formulation (running
            # max at d64, local-softmax + segment merge at d128), group-
            # unrolled loops with compile-time diagonal split; backward is
            # the fused single-pass kernel where its resident set fits
            # (measured UNDER jax.grad: fused 148 vs 121 two-pass at d64,
            # 279 vs 238 at d128 — standalone kernel timings invert this,
            # the composed program schedules three pallas calls worse than
            # two). Remaining d64 gap is the per-dot issue rate at K=64:
            # ~2 concurrent MXU streams regardless of tile shape/unroll
            "roofline": "d64 halves MXU-> ceiling ~0.5; d128 ceiling 1.0"}


def bench_yoloe_infer(backend):
    """BASELINE config 4: PP-YOLOE conv-heavy inference through the
    Predictor (reference serving path `inference/tests/api/` pattern).
    Same deploy shape as ResNet: NHWC + bf16 export + long spans."""
    import paddle_tpu as paddle
    from paddle_tpu import models

    if backend != "tpu":
        return {"skipped": "needs real chip"}
    batch, img = 64, 640
    paddle.seed(0)
    net = models.ppyoloe_s(data_format="NHWC")
    med, spread = _predictor_rate(net, (batch, img, img, 3), 500, 5,
                                  precision="bfloat16")
    return {"imgs_per_sec": round(med, 2), "spread": round(spread, 3),
            "batch": batch, "img": img, "layout": "NHWC", "precision": "bf16",
            "variant": "ppyoloe_s"}


def bench_ocr_rec_infer(backend):
    """BASELINE config 4, recognition half: PP-OCRv3-style CRNN (conv
    backbone -> BiLSTM -> CTC head) through the Predictor. Completes the
    config-4 pair next to bench_yoloe_infer (detection half)."""
    import paddle_tpu as paddle
    from paddle_tpu import models

    if backend != "tpu":
        return {"skipped": "needs real chip"}
    batch, h, w = 64, 32, 320
    paddle.seed(0)
    net = models.pp_ocrv3_rec(n_classes=6625, scale=0.5, hidden_size=48)
    # ~1 ms/step at batch 64: spans must be LONG or host-dispatch jitter
    # on the tunnel dominates (spread 0.9 at 200-step spans, 0.05 at 800)
    med, spread = _predictor_rate(net, (batch, h, w, 3), 800, 5,
                                  precision="bfloat16")
    return {"imgs_per_sec": round(med, 2), "spread": round(spread, 3),
            "batch": batch, "img": f"{h}x{w}", "layout": "NHWC",
            "precision": "bf16", "variant": "pp_ocrv3_rec (CRNN+BiLSTM+CTC)"}


def bench_ernie10b_layer(backend):
    """BASELINE config 5 proxy: ERNIE-3.0-Titan 10B layer-scale train step
    that fits one chip. FOUR transformer layers at the titan geometry
    (h=4096, ffn=16384, 64 heads — ~201M params/layer; 4 layers + AdamW
    state = ~13 GB, what one chip of a 12-way sharding+pipeline pod slice
    holds) run fwd+bwd+AdamW at seq 2048 through the scan-over-layers
    stack with per-layer remat (models/ernie.py ErnieScanStack — the same
    machinery the full 48-layer model trains with). MFU extrapolates
    per-layer. The full-model ZeRO-3 / pp x mp / SP-ring+flash regimes and
    the 16 GB/chip memory arithmetic are certified by
    __graft_entry__.dryrun_multichip and tests/test_titan_feasibility.py.
    """
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.models.ernie import ErnieScanStack
    from paddle_tpu.jit import TrainStep

    if backend != "tpu":
        return {"skipped": "needs real chip"}
    h, ffn, heads, seq, batch, nlayers = 4096, 16384, 64, 2048, 2, 4
    paddle.seed(0)
    net = ErnieScanStack(h, heads, ffn, nlayers, remat="dots")

    def loss_fn(out):
        # target-free MSE-to-zero: shipping a [10,2,2048,4096] zeros target
        # through the tunnel would cost 671MB of H2D for nothing
        return (out ** 2).mean()

    opt = paddle.optimizer.AdamW(parameters=net.parameters(), learning_rate=1e-4)
    step = TrainStep(net, loss_fn, opt, amp_dtype="bfloat16", n_model_inputs=1)
    n_steps = 10
    x = paddle.to_tensor(
        np.random.rand(n_steps, batch, seq, h).astype(np.float32) * 0.02)
    _sync(step.run(x)._value)  # compile + warmup
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        _sync(step.run(x)._value)
        rates.append(n_steps / (time.perf_counter() - t0))
    sps = statistics.median(rates)  # steps/s over the 2-layer block
    # per-layer matmul params: qkv+o (4h^2) + mlp (2*h*ffn)
    n_matmul = 4 * h * h + 2 * h * ffn
    flops_step = batch * (6 * n_matmul * seq + 3 * 4 * seq * seq * h)
    mfu = sps * nlayers * flops_step / PEAK_FLOPS
    ms_layer = 1000.0 / (sps * nlayers) / batch
    return {"layer_step_ms_per_sample": round(ms_layer, 2), "mfu": round(mfu, 4),
            "geometry": f"h{h}xffn{ffn}x{heads}head seq{seq}",
            "note": f"one-chip proxy: {nlayers} titan layers, scanned + "
                    "selective remat (jax.checkpoint dots+flash-out "
                    "saveable policy: backward replays only elementwise/"
                    "LN; blanket remat capped MFU at 0.326 in r4, no-remat "
                    "OOMs at 17.7G) + bf16 scan carry (r4 traced the raw-"
                    "jnp layer with an fp32 carry, silently promoting "
                    "every dot to fp32); ZeRO-3, pp x mp, SP-ring+flash "
                    "certified by dryrun_multichip; HBM arithmetic by "
                    "tests/test_titan_feasibility.py"}


def bench_allreduce(backend):
    """BASELINE config 3 metric: Fleet allreduce bus bandwidth (reference
    pattern `collective_allreduce_api.py:1`). A single axon chip has no ICI
    peer, so the collective runs on the 8-device virtual CPU mesh in a
    subprocess — it validates the collective path end-to-end and reports
    host-mesh bus bytes/s; real ICI bandwidth needs a multi-chip slice."""
    import subprocess
    import sys as _sys
    code = r"""
import os, sys, time, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, %r)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.parallel import create_mesh

n = jax.device_count()
nbytes = 64 << 20          # per-device payload (nccl-tests convention)
mesh = create_mesh({"dp": n})

def body(x):
    return dist.all_reduce(paddle.to_tensor(x))._value

f = jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                      check_rep=False))
x = jnp.ones((n, nbytes // 4), jnp.float32)
y = f(x)
float(np.asarray(y[0, 0]))  # warmup + path check
reps = 10
t0 = time.perf_counter()
for _ in range(reps):
    y = f(y)
float(np.asarray(y[0, 0]))
dt = (time.perf_counter() - t0) / reps
bus = 2 * (n - 1) / n * nbytes / dt
print(json.dumps({"bus_gbps": round(bus / 1e9, 3), "n_devices": n,
                  "payload_mb": nbytes >> 20}))
""" % os.path.dirname(os.path.abspath(__file__))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_PLATFORM_NAME")}
    try:
        proc = subprocess.run([_sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=600)
        if proc.returncode != 0 or not proc.stdout.strip():
            return {"error": f"rc={proc.returncode}",
                    "stderr_tail": proc.stderr[-400:]}
        out = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001
        return {"error": str(e)[:200]}
    out["note"] = ("correctness-smoke of the collective path on the 8-dev "
                   "virtual CPU mesh — NOT a bandwidth number; real ICI BW "
                   "needs a multi-chip slice")
    return out


def _init_backend(max_tries=None, backoff_s=None):
    """Backend init with bounded retry + backoff. A TPU-tunnel outage used
    to surface as rc=1 with no artifact; now the harness gets a structured
    {"outage": true} JSON line (rc=0) it can record and alert on, instead
    of an empty run. This is the ONLY place the backend is probed directly;
    every workload runs under _run_workload so a MID-RUN outage (the
    BENCH_r05 hole: a workload touching the dead tunnel after a clean init
    exited rc=1 artifactless) also lands here as structured JSON.

    BENCH_INIT_RETRIES / BENCH_INIT_BACKOFF_S override the retry budget
    (the regression test simulates an outage and must not sleep 15s)."""
    if max_tries is None:
        max_tries = int(os.environ.get("BENCH_INIT_RETRIES", 3))
    if backoff_s is None:
        backoff_s = float(os.environ.get("BENCH_INIT_BACKOFF_S", 5.0))
    errors = []
    for attempt in range(1, max_tries + 1):
        try:
            import jax
            return jax.default_backend()
        except Exception as e:  # noqa: BLE001 — runtime/tunnel init failure
            errors.append(f"attempt {attempt}: {type(e).__name__}: "
                          f"{str(e)[:200]}")
            if attempt < max_tries:
                time.sleep(backoff_s * attempt)
    _emit_outage("backend_init", errors, {})
    sys.exit(0)


def _emit_outage(stage, errors, partial_extra):
    """The structured outage artifact (rc=0): the harness records WHAT died
    and keeps every result measured before the outage."""
    print(json.dumps({"outage": True, "stage": stage,
                      "errors": errors if isinstance(errors, list)
                      else [errors],
                      "partial_extra": partial_extra}))


_OUTAGE_MARKERS = ("unavailable", "deadline", "tunnel", "connection",
                   "connect", "socket", "unreachable", "aborted",
                   "internal: failed", "backend", "timed out", "timeout")


def _is_outage(e) -> bool:
    """A backend/tunnel outage, as opposed to a workload bug: runtime/OS
    transport errors, or XLA runtime errors carrying transport markers."""
    if isinstance(e, (ConnectionError, TimeoutError, BrokenPipeError,
                      OSError)):
        return True
    name = type(e).__name__
    msg = str(e).lower()
    if name in ("XlaRuntimeError", "FailedPreconditionError"):
        return True
    return isinstance(e, RuntimeError) and any(m in msg
                                               for m in _OUTAGE_MARKERS)


def bench_serving_slo(backend):
    """Serving observability tax A/B: per-request engine latency with the
    request-tracing + SLO planes off vs on (FLAGS_trace, FLAGS_slo_*).
    Both arms run with the monitor on, so the delta isolates exactly what
    this plane adds: span bookkeeping per request plus the sketch/burn
    accounting. Also reports the traced arm's sketch quantiles and burn
    rate — the numbers the 'PDHQ' probe serves to the router."""
    import paddle_tpu.monitor as monitor
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.obs import slo as _slo, trace as _trace
    from paddle_tpu.serving import engine as _eng

    n = 400 if backend == "tpu" else 200

    def one_arm(trace_on):
        _flags.set_flags({
            "monitor": True,
            "trace": trace_on,
            "slo_latency_ms": 50.0 if trace_on else 0.0,
        })
        eng = _eng.ServingEngine(lambda arrays: arrays).start()
        x = np.random.rand(1, 16).astype("float32")
        try:
            for _ in range(20):            # warm the bucket executable
                eng.submit([x]).result(timeout=10)
            t0 = time.perf_counter()
            for _ in range(n):
                eng.submit([x]).result(timeout=10)
            per_req_us = (time.perf_counter() - t0) / n * 1e6
            stats = eng.stats()
        finally:
            eng.stop()
            _flags.set_flags({"monitor": False, "trace": False,
                              "slo_latency_ms": 0.0})
            _trace.reset()
            _slo.reset()
            monitor.reset()
        return per_req_us, stats

    base_us, _ = one_arm(False)
    traced_us, stats = one_arm(True)
    slo = stats.get("slo") or {}
    out = {
        "requests_per_arm": n,
        "per_request_us_off": round(base_us, 1),
        "per_request_us_on": round(traced_us, 1),
        "overhead_pct": round((traced_us - base_us) / base_us * 100, 1)
        if base_us else None,
        "latency_ms": {k: round(v, 3) for k, v in
                       (slo.get("latency_ms") or {}).items()},
        "burn": slo.get("burn"),
    }
    return out


def bench_telemetry(backend):
    """Fleet-telemetry tax A/B (obs/telemetry.py): the same train-step
    loop and serving burst with the exporter off vs on — on means a live
    TelemetryCollector plus a TelemetryExporter shipping delta counters,
    mergeable sketches, and events every FLAGS_telemetry_interval_s. The
    exporter's hot-path contract (event() appends to a deque; every
    socket op lives on the export thread) targets <=2% tax on both the
    train samples/s and the serving p99.

    Knob: BENCH_TELEMETRY=ab|off (default ab runs both arms)."""
    import paddle_tpu as paddle
    import paddle_tpu.monitor as monitor
    import paddle_tpu.nn as nn
    from paddle_tpu import models
    from paddle_tpu._native import TCPStore
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.obs import telemetry as _telemetry
    from paddle_tpu.serving import engine as _eng

    if os.environ.get("BENCH_TELEMETRY", "ab").lower() == "off":
        return {"skipped": "BENCH_TELEMETRY=off"}

    batch, seqlen = (32, 128) if backend == "tpu" else (8, 64)
    n_steps = 30 if backend == "tpu" else 6
    n_req = 400 if backend == "tpu" else 200

    paddle.seed(0)
    base = models.ernie_base(hidden_dropout_prob=0.0) \
        if backend == "tpu" else \
        models.ErnieModel(vocab_size=1024, hidden_size=128,
                          num_hidden_layers=2, num_attention_heads=4,
                          intermediate_size=512, hidden_dropout_prob=0.0)
    net = models.ErnieForPretraining(base)
    ce = nn.CrossEntropyLoss()

    def loss_fn(logits, nsp_logits, ids, nsp):
        v = logits.shape[-1]
        return ce(logits.reshape([-1, v]), ids.reshape([-1])) \
            + ce(nsp_logits, nsp)

    opt = paddle.optimizer.AdamW(parameters=net.parameters(),
                                 learning_rate=1e-4)
    step = TrainStep(net, loss_fn, opt, amp_dtype="bfloat16",
                     n_model_inputs=1)
    vocab = base.embeddings.word_embeddings.weight.shape[0]
    ids = paddle.to_tensor(np.random.randint(
        0, vocab, (batch, seqlen)).astype(np.int32))
    nsp = paddle.to_tensor(np.random.randint(
        0, 2, (batch,)).astype(np.int32))
    _sync(step(ids, ids, nsp)._value)   # compile outside both arms

    def one_arm(on):
        _flags.set_flags({"monitor": True, "telemetry": on})
        store = col = exp = None
        if on:
            store = TCPStore("127.0.0.1", 0, is_master=True)
            col = _telemetry.TelemetryCollector(
                store, fleet="bench").start()
            exp = _telemetry.TelemetryExporter(
                store, source="bench-0", role="replica",
                fleet="bench").start()
        try:
            sps = 0.0
            for _ in range(3):                # best-of: dodge CPU noise
                t0 = time.perf_counter()
                loss = None
                for _ in range(n_steps):
                    loss = step(ids, ids, nsp)
                _sync(loss._value)
                sps = max(sps,
                          batch * n_steps / (time.perf_counter() - t0))

            eng = _eng.ServingEngine(lambda arrays: arrays).start()
            x = np.random.rand(1, 16).astype("float32")
            p99s = []
            try:
                for _ in range(20):           # warm the bucket executable
                    eng.submit([x]).result(timeout=10)
                for _ in range(3):            # median p99: the tail of a
                    lat = []                  # short burst is noisy
                    for i in range(n_req):
                        t1 = time.perf_counter()
                        eng.submit([x]).result(timeout=10)
                        lat.append(time.perf_counter() - t1)
                        if on and i % 25 == 0:   # realistic event cadence
                            exp.event("rollout", seq=i)
                    p99s.append(float(np.quantile(lat, 0.99)))
            finally:
                eng.stop()
            p99_us = float(np.median(p99s)) * 1e6
            pushes = exp.pushes if on else 0
        finally:
            if exp is not None:
                exp.stop()
            if col is not None:
                col.stop()
            _flags.set_flags({"monitor": False, "telemetry": False})
            monitor.reset()
        return sps, p99_us, pushes

    sps_off, p99_off, _ = one_arm(False)
    sps_on, p99_on, pushes = one_arm(True)
    return {
        "train_steps_per_arm": n_steps,
        "requests_per_arm": n_req,
        "pushes_on_arm": pushes,
        "train_sps_off": round(sps_off, 2),
        "train_sps_on": round(sps_on, 2),
        "train_tax_pct": round((sps_off - sps_on) / sps_off * 100, 2)
        if sps_off else None,
        "serving_p99_us_off": round(p99_off, 1),
        "serving_p99_us_on": round(p99_on, 1),
        "serving_p99_tax_pct": round((p99_on - p99_off) / p99_off * 100, 2)
        if p99_off else None,
    }


def bench_sync(backend):
    """Runtime concurrency-sanitizer tax A/B (utils/syncwatch.py): the
    same serving burst with FLAGS_sync_watch off vs on. On the on arm
    the engine's dispatch lock (and every other factory-built lock
    constructed under the flag) is a watched wrapper doing held-set +
    order-graph bookkeeping per outermost acquire; the acceptance target
    is <=2% serving p99 tax. Off-arm locks are plain `threading.Lock`
    (the PR-1 one-attribute-check contract), so the off arm IS the
    baseline.

    Knob: BENCH_SYNC=ab|off (default ab runs both arms)."""
    import paddle_tpu.monitor as monitor
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.serving import engine as _eng
    from paddle_tpu.utils import syncwatch as _syncwatch

    if os.environ.get("BENCH_SYNC", "ab").lower() == "off":
        return {"skipped": "BENCH_SYNC=off"}

    n_req = 400 if backend == "tpu" else 200

    def one_arm(on):
        _flags.set_flags({"sync_watch": on})
        _syncwatch._reset()
        try:
            # engine constructed UNDER the flag: its dispatch lock is
            # watched on the on arm, plain on the off arm
            eng = _eng.ServingEngine(lambda arrays: arrays).start()
            x = np.random.rand(1, 16).astype("float32")
            p99s = []
            try:
                for _ in range(20):           # warm the bucket executable
                    eng.submit([x]).result(timeout=10)
                for _ in range(3):            # median p99: the tail of a
                    lat = []                  # short burst is noisy
                    for _ in range(n_req):
                        t1 = time.perf_counter()
                        eng.submit([x]).result(timeout=10)
                        lat.append(time.perf_counter() - t1)
                    p99s.append(float(np.quantile(lat, 0.99)))
            finally:
                eng.stop()
            return float(np.median(p99s)) * 1e6, _syncwatch.violations()
        finally:
            _flags.set_flags({"sync_watch": False})
            _syncwatch._reset()
            monitor.reset()

    p99_off, _ = one_arm(False)
    p99_on, violations = one_arm(True)
    return {
        "requests_per_arm": n_req,
        "serving_p99_us_off": round(p99_off, 1),
        "serving_p99_us_on": round(p99_on, 1),
        "serving_p99_tax_pct": round((p99_on - p99_off) / p99_off * 100, 2)
        if p99_off else None,
        "order_violations": violations,
    }


def bench_autoscale(backend):
    """Elastic-autoscaler drill + decision-loop tax (serving/autoscaler.py).

    Drill: one warm in-process replica, a client burst saturates its
    queue, the sense->decide->act loop grows the pool —
    time_to_first_new_replica_ms is spike-start -> the new replica
    HEALTHY (spawn + register + first probe), recovery_window_ms is
    spike-end -> the sensed signal back under every scale-out threshold.

    Tax A/B: the same serving burst with the tick loop off vs on against
    a PINNED pool (min==max: every tick senses, decides `hold`,
    publishes — the full loop minus actuation). decision_loop_tax_pct
    compares serving p99; the acceptance target is <=1%.

    Knob: BENCH_AUTOSCALE=ab|off (default ab runs both)."""
    import threading

    import paddle_tpu.monitor as monitor
    from paddle_tpu._native import TCPStore
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.obs import telemetry as _telemetry
    from paddle_tpu.serving import (Autoscaler, EngineConfig, FleetRouter,
                                    ReplicaAgent, ReplicaPool, ScalePolicy)

    if os.environ.get("BENCH_AUTOSCALE", "ab").lower() == "off":
        return {"skipped": "BENCH_AUTOSCALE=off"}

    saved = {k: _flags.flag(k) for k in
             ("monitor", "telemetry", "telemetry_interval_s",
              "serving_queue_depth", "fleet_heartbeat_s",
              "fleet_lease_ttl_s", "fleet_health_interval_s")}
    _flags.set_flags({"monitor": True, "telemetry": True,
                      "telemetry_interval_s": 0.05,
                      "serving_queue_depth": 4,
                      "fleet_heartbeat_s": 0.1, "fleet_lease_ttl_s": 0.4,
                      "fleet_health_interval_s": 0.1})
    x = np.full((1, 8), 1.0, np.float32)

    def spawn_fn(store, model_s):
        def handler(a):
            time.sleep(model_s)
            return a * 2.0
        def spawn():
            agent = ReplicaAgent(
                handler, store, fleet="bench-as",
                engine_config=EngineConfig(max_batch_size=8,
                                           batch_timeout_ms=1.0,
                                           warmup_on_start=False))
            try:
                return agent.start()
            except BaseException:
                agent.stop(drain=False)
                raise
        return spawn

    def plane(model_s):
        store = TCPStore("127.0.0.1", 0, is_master=True)
        col = _telemetry.TelemetryCollector(store, fleet="bench-as").start()
        router = FleetRouter(store, fleet="bench-as").start()
        pool = ReplicaPool(router, spawn_fn(store, model_s),
                           spawn_timeout_s=60.0)
        return store, col, router, pool

    out = {}
    try:
        # ---- drill: spike -> grow -> recover --------------------------
        store, col, router, pool = plane(0.003)
        policy = ScalePolicy(burn_high=1e9, burn_low=0.0,
                             queue_high=0.5, queue_low=0.2,
                             min_replicas=1, max_replicas=3,
                             cooldown_s=0.5, idle_after_s=30.0,
                             zero_after_s=3600.0, step=1)
        auto = Autoscaler(col, pool, policy=policy, interval_s=0.1,
                          queue_capacity=4)
        stop = threading.Event()

        def client():
            while not stop.is_set():
                try:
                    router.run([x], deadline_ms=8000)
                except Exception:
                    pass

        try:
            auto.start()
            deadline = time.monotonic() + 60
            while pool.actual() < 1 and time.monotonic() < deadline:
                time.sleep(0.02)
            threads = [threading.Thread(target=client) for _ in range(8)]
            spike_at = time.monotonic()
            [t.start() for t in threads]
            while pool.actual() < 2 and time.monotonic() < deadline:
                time.sleep(0.005)
            t_first = time.monotonic() - spike_at
            stop.set()
            [t.join() for t in threads]
            calm_at = time.monotonic()
            recovery = None
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                sig = auto._sense()
                if sig["queue_frac"] < policy.queue_low \
                        and sig["pending"] == 0:
                    recovery = time.monotonic() - calm_at
                    break
                time.sleep(0.02)
            out["grew_to"] = pool.actual()
            out["time_to_first_new_replica_ms"] = round(t_first * 1e3, 1)
            out["recovery_window_ms"] = (round(recovery * 1e3, 1)
                                         if recovery is not None else None)
            out["decisions"] = auto.ledger.snapshot()["counts"]
        finally:
            stop.set()
            auto.close(stop_pool=True)
            router.close()
            col.stop()

        # ---- tax A/B: pinned pool, loop off vs on ---------------------
        n_req = 400 if backend == "tpu" else 200

        def one_arm(loop_on):
            store, col, router, pool = plane(0.0)
            auto = None
            try:
                # bootstrap the single replica through the pool either
                # way, so both arms serve through an identical stack
                pool.scale_out(1)
                if loop_on:
                    auto = Autoscaler(
                        col, pool,
                        policy=ScalePolicy(min_replicas=1, max_replicas=1,
                                           cooldown_s=0.5),
                        interval_s=0.05, queue_capacity=4)
                    auto.start()
                for _ in range(20):                       # warm the path
                    router.run([x], deadline_ms=8000)
                p99s = []
                for _ in range(3):                # median p99: short-burst
                    lat = []                      # tails are noisy on CPU
                    for _ in range(n_req):
                        t1 = time.perf_counter()
                        router.run([x], deadline_ms=8000)
                        lat.append(time.perf_counter() - t1)
                    p99s.append(float(np.quantile(lat, 0.99)))
                ticks = auto.ticks if auto is not None else 0
                return float(np.median(p99s)) * 1e6, ticks
            finally:
                if auto is not None:
                    auto.close(stop_pool=False)
                pool.stop_all()
                router.close()
                col.stop()

        p99_off, _ = one_arm(False)
        p99_on, ticks = one_arm(True)
        out["requests_per_arm"] = n_req
        out["ticks_on_arm"] = ticks
        out["serving_p99_us_off"] = round(p99_off, 1)
        out["serving_p99_us_on"] = round(p99_on, 1)
        out["decision_loop_tax_pct"] = (
            round((p99_on - p99_off) / p99_off * 100, 2)
            if p99_off else None)
    finally:
        _flags.set_flags(saved)
        monitor.reset()
    return out


def bench_net(backend):
    """One-wire substrate tax A/B (utils/net.py): serving request p99
    and PS dense-push throughput through the RpcChannel substrate vs a
    hand-rolled PRE-substrate wire client (same bytes, no channel, no
    fault sites, no retry loop) against the same live servers — the tax
    target is <=2% on both. A third arm re-runs the substrate clients
    with FLAGS_net_auth_token set, measuring what the 'PDAR' HMAC
    record layer costs when the fleet flips the one security flag.

    Knob: BENCH_NET=ab|off (default ab runs both arms)."""
    import socket as _socket
    import struct as _struct
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.distributed.ps.service import (_HDR, CMD_PUSH_DENSE,
                                                   PsClient, PsServer,
                                                   _tname)
    from paddle_tpu.inference.server import (_REQ_MAGIC, PredictorClient,
                                             PredictorServer,
                                             _read_tensor, _write_tensor)
    from paddle_tpu.serving import EngineConfig

    if os.environ.get("BENCH_NET", "ab").lower() == "off":
        return {"skipped": "BENCH_NET=off"}

    n_req = 400 if backend == "tpu" else 200
    n_push = 300 if backend == "tpu" else 150
    dense_n = 4096
    x = np.random.rand(1, 16).astype(np.float32)
    g = np.ones(dense_n, np.float32)

    srv = PredictorServer(lambda a: a, engine_config=EngineConfig(
        warmup_on_start=False)).start()
    ps = PsServer()
    ps.add_dense_table("w", dense_n, lr=0.1)
    ps.run()

    def serving_p99_legacy():
        s = _socket.create_connection((srv.host, srv.port), timeout=30)
        s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)

        def one():
            s.sendall(_struct.pack("<II", _REQ_MAGIC, 1))
            _write_tensor(s, x)
            hdr = b""
            while len(hdr) < 9:
                hdr += s.recv(9 - len(hdr))
            _read_tensor(s)

        try:
            for _ in range(20):
                one()                     # warm the bucket executable
            p99s = []
            for _ in range(3):
                lat = []
                for _ in range(n_req):
                    t0 = time.perf_counter()
                    one()
                    lat.append(time.perf_counter() - t0)
                p99s.append(float(np.quantile(lat, 0.99)))
            return float(np.median(p99s)) * 1e6
        finally:
            s.close()

    def serving_p99_substrate():
        client = PredictorClient(srv.host, srv.port)
        try:
            for _ in range(20):
                client.run([x])
            p99s = []
            for _ in range(3):
                lat = []
                for _ in range(n_req):
                    t0 = time.perf_counter()
                    client.run([x])
                    lat.append(time.perf_counter() - t0)
                p99s.append(float(np.quantile(lat, 0.99)))
            return float(np.median(p99s)) * 1e6
        finally:
            client.close()

    def push_rate_legacy():
        s = _socket.create_connection((ps.host, ps.port), timeout=30)
        s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        frame = _HDR.pack(CMD_PUSH_DENSE, _tname("w"), dense_n, 0) \
            + g.tobytes()
        try:
            rates = []
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(n_push):
                    s.sendall(frame)
                    if s.recv(1) != b"\x01":
                        raise RuntimeError("push rejected")
                rates.append(n_push / (time.perf_counter() - t0))
            return float(np.median(rates))
        finally:
            s.close()

    def push_rate_substrate():
        client = PsClient([f"{ps.host}:{ps.port}"], call_timeout=30.0)
        try:
            client.push_dense("w", g)     # learn the shard split first
            rates = []
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(n_push):
                    client.push_dense("w", g)
                rates.append(n_push / (time.perf_counter() - t0))
            return float(np.median(rates))
        finally:
            client.close()

    try:
        p99_legacy = serving_p99_legacy()
        p99_sub = serving_p99_substrate()
        push_legacy = push_rate_legacy()
        push_sub = push_rate_substrate()
        # flag flip: fresh connections negotiate the HMAC record layer
        _flags.set_flags({"net_auth_token": "bench-token"})
        try:
            p99_auth = serving_p99_substrate()
            push_auth = push_rate_substrate()
        finally:
            _flags.set_flags({"net_auth_token": ""})
    finally:
        srv.stop()
        ps.stop()

    return {
        "requests_per_arm": n_req,
        "pushes_per_arm": n_push,
        "serving_p99_us_legacy": round(p99_legacy, 1),
        "serving_p99_us_substrate": round(p99_sub, 1),
        "serving_p99_tax_pct": round(
            (p99_sub - p99_legacy) / p99_legacy * 100, 2),
        "serving_p99_us_auth": round(p99_auth, 1),
        "serving_auth_overhead_pct": round(
            (p99_auth - p99_sub) / p99_sub * 100, 2),
        "ps_push_per_s_legacy": round(push_legacy, 1),
        "ps_push_per_s_substrate": round(push_sub, 1),
        "ps_push_tax_pct": round(
            (push_legacy - push_sub) / push_legacy * 100, 2),
        "ps_push_per_s_auth": round(push_auth, 1),
        "ps_push_auth_overhead_pct": round(
            (push_sub - push_auth) / push_sub * 100, 2),
    }


def bench_ps_durability(backend):
    """PS durability tax A/B: sequenced sparse-push throughput with the
    WAL off vs on (FLAGS_ps_wal_dir), plus the recovery path timed —
    snapshot, then a cold restart that loads the snapshot and replays
    the post-snapshot WAL suffix. The delta between arms is exactly what
    the durability plane adds per push: one CRC-framed append + fsync
    policy; the recovery numbers bound how long a standby-less restart
    keeps trainers waiting.

    Knob: BENCH_PS=ab|on|off (default ab runs both arms)."""
    import shutil
    import tempfile
    from paddle_tpu.distributed.ps import PsClient, PsServer

    arm_cfg = os.environ.get("BENCH_PS", "ab").lower()
    if arm_cfg == "off":
        return {"skipped": "BENCH_PS=off"}
    n_push, batch, dim = 300, 64, 16
    ids = np.arange(batch, dtype=np.int64)
    grads = np.ones((batch, dim), np.float32)

    def one_arm(wal_dir):
        server = PsServer("127.0.0.1", 0, wal_dir=wal_dir)
        server.run()
        client = PsClient([f"127.0.0.1:{server.port}"])
        try:
            client.create_sparse_table("emb", dim, optimizer="sgd",
                                       lr=0.1, seed=7)
            client.push_sparse("emb", ids, grads)   # warm the table rows
            t0 = time.perf_counter()
            for _ in range(n_push):
                client.push_sparse("emb", ids, grads)
            per_push_us = (time.perf_counter() - t0) / n_push * 1e6
        finally:
            client.close()
            server.stop()
        return per_push_us

    out = {"pushes_per_arm": n_push, "batch": batch, "dim": dim}
    wal_dir = tempfile.mkdtemp(prefix="bench-ps-wal-")
    try:
        if arm_cfg == "ab":
            out["per_push_us_off"] = round(one_arm(None), 1)
        out["per_push_us_on"] = round(one_arm(wal_dir), 1)
        if "per_push_us_off" in out and out["per_push_us_off"]:
            out["overhead_pct"] = round(
                (out["per_push_us_on"] - out["per_push_us_off"])
                / out["per_push_us_off"] * 100, 1)

        # recovery path: snapshot, append a WAL suffix, cold restart
        server = PsServer("127.0.0.1", 0, wal_dir=wal_dir)
        server.run()
        client = PsClient([f"127.0.0.1:{server.port}"])
        try:
            t0 = time.perf_counter()
            server.snapshot()
            out["snapshot_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
            for _ in range(50):
                client.push_sparse("emb", ids, grads)
        finally:
            client.close()
            server.stop()
        t0 = time.perf_counter()
        server = PsServer("127.0.0.1", 0, wal_dir=wal_dir)
        out["recover_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        out["recovered_lsn"] = server.applied_lsn
        server.stop()
    finally:
        shutil.rmtree(wal_dir, ignore_errors=True)
    return out


def bench_online(backend):
    """Online-serving delta plane: (a) the delta-push tax — sequenced
    sparse-push throughput with no subscriber vs with a DeltaSubscriber
    tailing the same table at the default cadence (the per-commit
    version bookkeeping is always on; the tax arm adds the concurrent
    delta pulls contending for the table lock), and (b) push ->
    servable visibility — how long after `push_sparse` returns until an
    `OnlineServingTable` lookup reflects the new value, reported as
    p50/p95/p99 over repeated rounds. (b) bounds the staleness a
    serving replica adds on top of the trainer's own push latency.

    Knob: BENCH_ONLINE=ab|on|off (default off: the arm spins a
    background tail thread and is not part of the BASELINE.md headline
    set)."""
    from paddle_tpu.distributed.ps import (DeltaSubscriber, PsClient,
                                           PsServer)
    from paddle_tpu.serving.online import OnlineServingTable

    if os.environ.get("BENCH_ONLINE", "off").lower() not in ("on", "ab"):
        return {"skipped": "BENCH_ONLINE=off"}
    dim, batch, n_push, n_vis = 16, 64, 300, 60
    ids = np.arange(batch, dtype=np.int64)
    grads = np.ones((batch, dim), np.float32)
    server = PsServer("127.0.0.1", 0)
    server.run()
    client = PsClient([f"127.0.0.1:{server.port}"])
    out = {"pushes_per_arm": n_push, "batch": batch, "dim": dim}
    sub = None
    try:
        client.create_sparse_table("emb", dim, optimizer="sgd", lr=0.1,
                                   seed=7)
        client.push_sparse("emb", ids, grads)   # warm the table rows

        t0 = time.perf_counter()
        for _ in range(n_push):
            client.push_sparse("emb", ids, grads)
        out["per_push_us_solo"] = round(
            (time.perf_counter() - t0) / n_push * 1e6, 1)

        tbl = OnlineServingTable("emb", dim)
        sub = DeltaSubscriber({"emb": tbl},
                              endpoint=f"127.0.0.1:{server.port}",
                              subscriber_id="bench",
                              pull_timeout_s=5.0).start()
        t0 = time.perf_counter()
        for _ in range(n_push):
            client.push_sparse("emb", ids, grads)
        out["per_push_us_tailed"] = round(
            (time.perf_counter() - t0) / n_push * 1e6, 1)
        out["tail_overhead_pct"] = round(
            (out["per_push_us_tailed"] - out["per_push_us_solo"])
            / out["per_push_us_solo"] * 100, 1)

        # push -> servable: poll the serving table until the pushed
        # value lands (sgd lr=0.1 on an all-ones grad moves every row
        # deterministically, so "landed" == first element changed)
        vis_ms = []
        probe = ids[:1]
        for _ in range(n_vis):
            before = tbl.lookup(probe)[0, 0]
            t0 = time.perf_counter()
            client.push_sparse("emb", ids, grads)
            while tbl.lookup(probe)[0, 0] == before:
                time.sleep(0.0005)
            vis_ms.append((time.perf_counter() - t0) * 1e3)
        lat = np.asarray(vis_ms)
        out["visibility_ms"] = {
            "p50": round(float(np.quantile(lat, 0.50)), 2),
            "p95": round(float(np.quantile(lat, 0.95)), 2),
            "p99": round(float(np.quantile(lat, 0.99)), 2)}
        out["staleness_s_at_probe"] = round(tbl.staleness_s(), 4)
    finally:
        if sub is not None:
            sub.stop()
        client.close()
        server.stop()
    return out


def bench_llm(backend):
    """Continuous-batching LLM serving (serving/llm.py): concurrent
    variable-length requests through the slot-paged KV-cache engine.
    Reports prefill vs decode tokens/s, TTFT and inter-token latency
    histograms (p50/p95/p99), steady-state compile count (the zero-
    compile claim, measured), and — in the ab arm — the fp32 vs int8
    weight-only A/B (BENCH_r08 follow-on to resnet50_infer_int8, but on
    the decode path where weight HBM reads dominate).

    Knob: BENCH_LLM=on|ab|off (default ab runs both arms)."""
    import paddle_tpu as paddle
    import paddle_tpu.monitor as monitor
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTModel
    from paddle_tpu.serving.llm import LLMConfig, LLMEngine

    arm = os.environ.get("BENCH_LLM", "ab").lower()
    if arm == "off":
        return {"skipped": "BENCH_LLM=off"}
    big = backend == "tpu"
    vocab, n_req = 8192, (32 if big else 8)
    max_new = 48 if big else 16

    def one_arm(quant):
        paddle.seed(0)
        lm = GPTForCausalLM(GPTModel(
            vocab_size=vocab, hidden_size=256 if big else 64,
            num_layers=4 if big else 2, num_heads=8 if big else 4,
            max_seq_len=512, dropout=0.0))
        cfg = LLMConfig(num_slots=8, max_len=256 if big else 64,
                        max_new_tokens=max_new, quant=quant,
                        kv_int8=(quant == "int8"))
        _flags.set_flags({"monitor": True})
        monitor.reset()
        eng = LLMEngine(lm, cfg).start()   # warmup pays every compile
        rng = np.random.default_rng(0)
        lens = rng.integers(4, cfg.max_len - max_new, size=n_req)
        prompts = [rng.integers(0, vocab, size=int(L)).tolist()
                   for L in lens]
        c0 = monitor.snapshot()["counters"].get("trace_compile", 0)
        t0 = time.perf_counter()
        streams = [eng.submit(p) for p in prompts]
        results = [s.result(timeout=600.0) for s in streams]
        wall = time.perf_counter() - t0
        snap = monitor.snapshot()
        hist = snap["histograms"]
        compiles = snap["counters"].get("trace_compile", 0) - c0
        decode_toks = sum(len(t) for _, t in results)
        first_token = hist.get("llm.ttft_ms", {})
        inter = hist.get("llm.inter_token_ms", {})
        out = {
            "requests": n_req,
            "prefill_tokens_per_s": round(
                float(sum(lens)) / max(wall, 1e-9), 1),
            "decode_tokens_per_s": round(decode_toks / max(wall, 1e-9), 1),
            "ttft_ms": {k: round(first_token.get(k, 0.0), 2)
                        for k in ("p50", "p95", "p99")},
            "inter_token_ms": {k: round(inter.get(k, 0.0), 3)
                               for k in ("p50", "p95", "p99")},
            "steady_state_compiles": compiles,
            "kv_pool_mb": round(eng.kv_pool_bytes() / 2**20, 2),
            "warm_start_ms": round(eng.stats()["warm_start_ms"], 1),
        }
        eng.stop()
        monitor.reset()
        _flags.set_flags({"monitor": False})
        return out

    fp32 = one_arm("off")
    if arm != "ab":
        return fp32
    int8 = one_arm("int8")
    speedup = None
    if fp32.get("decode_tokens_per_s"):
        speedup = round(int8["decode_tokens_per_s"]
                        / fp32["decode_tokens_per_s"], 3)
    return {"fp32": fp32, "int8": int8, "int8_decode_speedup": speedup}


def _run_workload(name, fn, backend, partial_extra):
    """Run one bench workload. Outage -> structured {"outage": true} JSON
    (with everything measured so far) and rc=0; any other failure is
    recorded as that workload's {"error": ...} entry and the run
    continues — one broken bench no longer costs the whole artifact."""
    try:
        return fn(backend)
    except KeyboardInterrupt:
        raise
    except Exception as e:  # noqa: BLE001 — per-workload containment
        if _is_outage(e):
            _emit_outage(name, f"{type(e).__name__}: {str(e)[:300]}",
                         partial_extra)
            sys.exit(0)
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def main():
    backend = _init_backend()

    extra = {}
    ernie = _run_workload("ernie_train", bench_ernie_train, backend, extra)
    if isinstance(ernie, dict) and "overlap" in ernie:
        extra["overlap"] = ernie.pop("overlap")
    if isinstance(ernie, dict) and "memory" in ernie:
        extra["memory"] = ernie.pop("memory")
    flash = _run_workload("flash_attention", bench_flash_attention, backend,
                          extra)
    for key, fn in (("resnet50_infer", bench_resnet50_infer),
                    ("resnet50_infer_int8", bench_resnet50_infer_int8),
                    ("lenet_dispatch", bench_lenet_dispatch),
                    (f"flash_attn_{flash.get('seq', 'na')}",
                     lambda _b: flash),
                    ("yoloe_infer", bench_yoloe_infer),
                    ("ocr_rec_infer", bench_ocr_rec_infer),
                    ("ernie10b_layer", bench_ernie10b_layer),
                    ("allreduce_smoke", bench_allreduce),
                    ("serving_slo", bench_serving_slo),
                    ("telemetry", bench_telemetry),
                    ("sync", bench_sync),
                    ("autoscale", bench_autoscale),
                    ("net", bench_net),
                    ("ps_durability", bench_ps_durability),
                    ("online", bench_online),
                    ("llm", bench_llm),
                    ("warm_start", bench_warm_start)):
        extra[key] = _run_workload(key, fn, backend, extra)

    lenet = extra.get("lenet_dispatch")
    if isinstance(lenet, dict) and "lazy" in lenet:
        extra["lazy"] = lenet.pop("lazy")

    sps = ernie.get("samples_per_sec")
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "bench_baseline.json")
    vs = 1.0
    if sps and os.path.exists(baseline_path):
        try:
            with open(baseline_path) as f:
                refv = json.load(f).get("value")
            if refv:
                vs = sps / refv
        except Exception:
            pass
    tag = f"[{backend},b{ernie.get('batch')},s{ernie.get('seqlen')},bf16]"
    print(json.dumps({
        "metric": f"ernie_base_train_samples_per_sec_per_chip{tag}",
        "value": sps,
        "unit": "samples/s",
        "vs_baseline": round(vs, 3),
        "mfu": ernie.get("mfu"),
        "mfu_attributed": ernie.get("mfu_attributed"),
        "timeline_ms": ernie.get("timeline_ms"),
        "spread": ernie.get("spread"),
        "error": ernie.get("error"),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
