"""USER drive: deploy a quantized model end-to-end (conv net, not LeNet-only)."""
import os, sys, tempfile
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.jit import InputSpec, save, load
from paddle_tpu.inference import Config, create_predictor

paddle.seed(0)
net = models.resnet18(num_classes=16)   # real conv net with BN + downsample
net.eval()
td = tempfile.mkdtemp()
x = np.random.RandomState(0).rand(2, 3, 64, 64).astype("float32")

p32 = os.path.join(td, "fp32")
save(net, p32, input_spec=[InputSpec([2, 3, 64, 64], "float32")])
p8 = os.path.join(td, "int8")
save(net, p8, input_spec=[InputSpec([2, 3, 64, 64], "float32")], precision="int8")
s32 = os.path.getsize(p32 + ".pdiparams.npz")
s8 = os.path.getsize(p8 + ".pdiparams.npz")
print(f"1. artifact size fp32={s32>>10}KB int8={s8>>10}KB ratio={s8/s32:.2f}")
assert s8 < s32 * 0.4

def run(path, quant):
    cfg = Config(path)
    if quant:
        cfg.enable_quant()
    pred = create_predictor(cfg)
    pred.get_input_handle(pred.get_input_names()[0]).copy_from_cpu(x)
    pred.run()
    return pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()

ref = run(p32, False)
got = run(p8, True)
rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
print(f"2. int8 vs fp32 predictor rel err = {rel:.4f}")
assert rel < 0.1

tl = load(p8)
import jax.numpy as jnp
qnames = tl._meta["quantized"]
assert qnames and all(
    dict(zip(tl._meta["param_names"], tl._params))[n].dtype == jnp.int8
    for n in qnames)
print(f"3. {len(qnames)} weights stored int8 in the loaded artifact")

cfg = Config(p32); cfg.enable_quant()
try:
    create_predictor(cfg); raise SystemExit("expected error")
except Exception as e:
    assert "int8 artifact" in str(e)
print("4. enable_quant on fp32 artifact raises with hint")
print("ALL VERIFY DRIVES PASSED")
