import time, statistics
import numpy as np
import jax, jax.numpy as jnp
PEAK = 1.97e14; B = 128; N = 300

def scan_bench_w(op_with_w, x, w, n=N):
    @jax.jit
    def f(x, w):
        def body(c, _):
            o = op_with_w(x, w * (1.0 + c).astype(w.dtype))
            return o.reshape(-1)[0].astype(jnp.float32) * 1e-20, None
        c, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), None, length=n)
        return c
    r = f(x, w); r.block_until_ready()
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); float(np.asarray(f(x, w))); ts.append(time.perf_counter() - t0)
    return statistics.median(ts) / n

def conv(dn_in, dn_w, dn_out, stride=(1,1), pad=[(0,0),(0,0)]):
    return lambda a, w: jax.lax.conv_general_dilated(
        a, w, stride, pad, dimension_numbers=(dn_in, dn_w, dn_out))

# 1x1 convs, NCHW vs NHWC
for cin, cout, hw in ((64, 64, 56), (256, 64, 56), (64, 256, 56), (1024, 256, 14), (512, 2048, 7)):
    xk = jnp.zeros((B, cin, hw, hw), jnp.bfloat16)
    wk = jnp.zeros((cout, cin, 1, 1), jnp.bfloat16)
    dt = scan_bench_w(conv("NCHW", "OIHW", "NCHW"), xk, wk)
    fl = 2 * B * hw * hw * cin * cout
    xk2 = jnp.zeros((B, hw, hw, cin), jnp.bfloat16)
    wk2 = jnp.zeros((1, 1, cin, cout), jnp.bfloat16)
    dt2 = scan_bench_w(conv("NHWC", "HWIO", "NHWC"), xk2, wk2)
    # matmul formulation
    xm = jnp.zeros((B * hw * hw, cin), jnp.bfloat16)
    wm = jnp.zeros((cin, cout), jnp.bfloat16)
    dt3 = scan_bench_w(lambda a, w: jnp.matmul(a, w), xm, wm)
    print(f"1x1 c{cin:4d}->{cout:4d} hw{hw:3d}: NCHW {dt*1e3:.3f}ms mfu={fl/dt/PEAK:.3f} | "
          f"NHWC {dt2*1e3:.3f}ms mfu={fl/dt2/PEAK:.3f} | mm {dt3*1e3:.3f}ms mfu={fl/dt3/PEAK:.3f}", flush=True)

# 3x3 NCHW vs NHWC at stage2
for cin, hw in ((64, 56), (256, 14)):
    xk = jnp.zeros((B, cin, hw, hw), jnp.bfloat16)
    wk = jnp.zeros((cin, cin, 3, 3), jnp.bfloat16)
    dt = scan_bench_w(conv("NCHW", "OIHW", "NCHW", (1,1), [(1,1),(1,1)]), xk, wk)
    xk2 = jnp.zeros((B, hw, hw, cin), jnp.bfloat16)
    wk2 = jnp.zeros((3, 3, cin, cin), jnp.bfloat16)
    dt2 = scan_bench_w(conv("NHWC", "HWIO", "NHWC", (1,1), [(1,1),(1,1)]), xk2, wk2)
    fl = 2 * B * hw * hw * cin * cin * 9
    print(f"3x3 c{cin:3d} hw{hw:3d}: NCHW {dt*1e3:.3f}ms mfu={fl/dt/PEAK:.3f} | NHWC {dt2*1e3:.3f}ms mfu={fl/dt2/PEAK:.3f}", flush=True)
