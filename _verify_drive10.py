"""USER drive: elastic membership events + scale-out through the public API."""
import sys, time, threading
sys.path.insert(0, "/root/repo")
from paddle_tpu._native import TCPStore
from paddle_tpu.parallel.elastic import ElasticManager

store = TCPStore("127.0.0.1", 0, is_master=True)

# 1. watch_membership: steady -> scale_out on a join announcement
watcher = ElasticManager(store, rank=-1, world_size=2, lease_ttl=2.0)
m0 = ElasticManager(store, rank=0, world_size=2, lease_ttl=2.0,
                    heartbeat_interval=0.2).register()
m1 = ElasticManager(store, rank=1, world_size=2, lease_ttl=2.0,
                    heartbeat_interval=0.2).register()
evt, data = watcher.watch_membership(interval=0.2, max_wait=1.0)
assert evt == "steady", (evt, data)
print("1. steady membership OK")

def join():
    time.sleep(0.5)
    ElasticManager(store, rank=-1, world_size=0).announce_join("nodeX")
threading.Thread(target=join).start()
evt, tickets = watcher.watch_membership(interval=0.2, max_wait=10.0)
assert evt == "scale_out" and tickets == [1], (evt, tickets)
print("2. join announcement -> scale_out event, ticket", tickets)

# 3. absorbed tickets stop firing
evt, data = watcher.watch_membership(interval=0.2, max_wait=1.0,
                                     absorbed=tickets[-1])
assert evt == "steady", (evt, data)
print("3. absorbed ticket no longer pending")

# 4. scale_in still detected
m1.stop()
evt, dead = watcher.watch_membership(interval=0.3, max_wait=10.0,
                                     absorbed=tickets[-1])
assert evt == "scale_in" and dead == [1], (evt, dead)
print("4. dead rank -> scale_in event")
m0.stop()

# 5. end-to-end kill-AND-join with AutoCheckpoint resume: exercised by
# tests/test_elastic_io.py::TestElasticScaleOut (subprocess gang, ~25s);
# run it here as the driving scenario
import subprocess
r = subprocess.run([sys.executable, "-m", "pytest",
                    "/root/repo/tests/test_elastic_io.py::TestElasticScaleOut",
                    "-x", "-q"], capture_output=True, text=True, timeout=150)
assert r.returncode == 0, r.stdout[-800:]
print("5. kill-AND-join gang scenario passes end-to-end")
print("ALL VERIFY DRIVES PASSED")
