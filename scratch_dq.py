import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, ".")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, jax.numpy as jnp
from paddle_tpu.kernels.flash_attention import _flash_core, _reference_bhsd

rng = np.random.RandomState(0)
bh, s, d = 2, 256, 64
q = jnp.asarray(rng.rand(bh, s, d).astype("float32") - 0.5).astype(jnp.bfloat16)
k = jnp.asarray(rng.rand(bh, s, d).astype("float32") - 0.5).astype(jnp.bfloat16)
v = jnp.asarray(rng.rand(bh, s, d).astype("float32") - 0.5).astype(jnp.bfloat16)
q32, k32, v32 = (a.astype(jnp.float32) for a in (q, k, v))
causal = True

def f(a, b_, c):
    return (_flash_core(a, b_, c, causal, 128, 128, True).astype(jnp.float32) ** 2).sum()
def ref(a, b_, c):
    return (_reference_bhsd(a, b_, c, causal).astype(jnp.float32) ** 2).sum()

gk = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
gr32 = jax.grad(ref, argnums=(0, 1, 2))(q32, k32, v32)
grbf = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
for i, nm in enumerate(("dq", "dk", "dv")):
    a = np.asarray(gk[i], dtype=np.float32)
    w32 = np.asarray(gr32[i], dtype=np.float32)
    wbf = np.asarray(grbf[i], dtype=np.float32)
    print(nm, "kernel-vs-f32oracle:", np.abs(a - w32).max() / np.abs(w32).max(),
          " bf16ref-vs-f32oracle:", np.abs(wbf - w32).max() / np.abs(w32).max(),
          " kernel-vs-bf16ref:", np.abs(a - wbf).max() / np.abs(wbf).max())
