import time, statistics
import numpy as np
import jax, jax.numpy as jnp
N = 200

def run(f, args, n=N):
    r = f(*args); jax.tree_util.tree_map(lambda a: None, r)
    float(np.asarray(jax.tree_util.tree_leaves(r)[0].reshape(-1)[0]))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); float(np.asarray(jax.tree_util.tree_leaves(f(*args))[0].reshape(-1)[0])); ts.append(time.perf_counter() - t0)
    return statistics.median(ts)

rng = np.random.RandomState(0)
x = jnp.asarray(rng.rand(32, 128, 768).astype(np.float32)).astype(jnp.bfloat16)
g = jnp.asarray(rng.rand(768).astype(np.float32)).astype(jnp.bfloat16)
b = jnp.asarray(rng.rand(768).astype(np.float32)).astype(jnp.bfloat16)

def make(ln):
    @jax.jit
    def f(x, g, b):
        def body(c, _):
            def loss(x, g, b):
                return jnp.sum(ln(x * (1 + c).astype(x.dtype), g, b).astype(jnp.float32) ** 2)
            l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(x, g, b)
            return l * 1e-20, None
        return jax.lax.scan(body, jnp.zeros((), jnp.float32), None, length=N)[0]
    return f

def ln_bf16(x, g, b):
    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.var(x, axis=-1, keepdims=True)
    return ((x - m) * jax.lax.rsqrt(v + 1e-5)) * g + b

def ln_f32(x, g, b):
    x32 = x.astype(jnp.float32)
    m = jnp.mean(x32, axis=-1, keepdims=True)
    v = jnp.var(x32, axis=-1, keepdims=True)
    return (((x32 - m) * jax.lax.rsqrt(v + 1e-5)) * g.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)

# RTT baseline
@jax.jit
def empty(x, g, b):
    return x[0, 0, 0]
rtt = run(empty, (x, g, b), n=1)
print(f"rtt {rtt*1e3:.1f}ms")
for name, ln in (("bf16", ln_bf16), ("f32", ln_f32)):
    dt = (run(make(ln), (x, g, b)) - rtt) / N
    print(f"LN fwd+bwd {name}: {dt*1e6:.1f} us")
