import time, statistics
import numpy as np
import jax, jax.numpy as jnp
PEAK = 1.97e14; N = 300

def bench(f, *args, n=N):
    jf = jax.jit(f)
    r = jf(*args); float(np.asarray(jax.tree_util.tree_leaves(r)[0].reshape(-1)[0]))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); float(np.asarray(jax.tree_util.tree_leaves(jf(*args))[0].reshape(-1)[0])); ts.append(time.perf_counter() - t0)
    return statistics.median(ts) / n

# floor: tiny matmul in scan
x = jnp.zeros((128, 128), jnp.bfloat16)
def tiny(x):
    def body(c, _):
        o = jnp.matmul(x * (1 + c).astype(x.dtype), x)
        return o.reshape(-1)[0].astype(jnp.float32) * 1e-20, None
    return jax.lax.scan(body, jnp.zeros((), jnp.float32), None, length=N)[0]
print(f"tiny matmul/iter: {bench(tiny, x)*1e3:.4f} ms")

# chained 1x1 convs: 8 convs per iter, channel 256->256 hw56, feed forward
B = 128
xc = jnp.zeros((B, 256, 56, 56), jnp.bfloat16)
ws = [jnp.zeros((256, 256, 1, 1), jnp.bfloat16) for _ in range(8)]
def chain(x, *ws):
    def body(c, _):
        h = x
        for w in ws:
            h = jax.lax.conv_general_dilated(h, w * (1 + c).astype(w.dtype), (1, 1), [(0, 0), (0, 0)],
                                             dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return h.reshape(-1)[0].astype(jnp.float32) * 1e-20, None
    return jax.lax.scan(body, jnp.zeros((), jnp.float32), None, length=N)[0]
dt = bench(chain, xc, *ws)
fl = 8 * 2 * B * 56 * 56 * 256 * 256
print(f"1x1 c256 hw56 chained x8: {dt/8*1e3:.4f} ms/conv mfu={fl/dt/PEAK:.3f}")
