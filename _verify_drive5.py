"""USER drive: autocast enablement + cross-length guard + device_value."""
import os, sys, tempfile
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit import TrainStep, InputSpec, save
from paddle_tpu.parallel import HybridCommunicateGroup, SPMDTrainStep

rng = np.random.RandomState(0)

# 1. TrainStep with amp_dtype and a FP32 input: matmul must run bf16.
#    Spy via a layer that records its input dtype at trace time.
seen = {}
class Probe(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.fc2 = nn.Linear(32, 4)
    def forward(self, x):
        h = self.fc1(x)
        seen["hidden_dtype"] = h._value.dtype
        return self.fc2(h)
net = Probe()
opt = paddle.optimizer.SGD(parameters=net.parameters(), learning_rate=0.1)
step = TrainStep(net, lambda o, y: nn.CrossEntropyLoss()(o, y), opt,
                 amp_dtype="bfloat16", n_model_inputs=1)
x = paddle.to_tensor(rng.rand(8, 16).astype("float32"))
y = paddle.to_tensor(rng.randint(0, 4, (8,)).astype("int64"))
l0 = float(step(x, y))
for _ in range(10):
    l = float(step(x, y))
assert seen["hidden_dtype"] == jnp.bfloat16, seen
assert np.isfinite(l) and l < l0
print("1. TrainStep fp32-input autocast -> bf16 compute, loss descends", round(l0,3), "->", round(l,3))

# fp32 (no amp) unchanged
seen.clear()
net2 = Probe()
step2 = TrainStep(net2, lambda o, y: nn.CrossEntropyLoss()(o, y),
                  paddle.optimizer.SGD(parameters=net2.parameters(), learning_rate=0.1),
                  n_model_inputs=1)
step2(x, y)
assert seen["hidden_dtype"] == jnp.float32
print("2. no-amp path stays fp32")

# 3. SPMDTrainStep autocast on the mesh
seen.clear()
hcg = HybridCommunicateGroup(hybrid_configs={"dp_degree": 2, "mp_degree": 1})
net3 = Probe()
step3 = SPMDTrainStep(net3, nn.CrossEntropyLoss(),
                      paddle.optimizer.SGD(parameters=net3.parameters(), learning_rate=0.1),
                      mesh=hcg.get_mesh(), amp_dtype="bfloat16", donate=False)
step3(x, y)
assert seen["hidden_dtype"] == jnp.bfloat16
print("3. SPMDTrainStep autocast OK")

# 4. flash cross-length guard: q 2048 vs kv 1024 bf16 must NOT crash via sdpa
from paddle_tpu.nn.functional import scaled_dot_product_attention as sdpa
q = paddle.to_tensor(rng.rand(1, 2048, 2, 64).astype("float32")).astype("bfloat16")
kv = paddle.to_tensor(rng.rand(1, 1024, 2, 64).astype("float32")).astype("bfloat16")
out = sdpa(q, kv, kv)
assert tuple(out.shape) == (1, 2048, 2, 64)
print("4. cross-length attention takes fused path OK")
from paddle_tpu.kernels.flash_attention import flash_attention
try:
    flash_attention(q, kv, kv)
    raise SystemExit("expected ValueError")
except ValueError as e:
    assert "share seq_len" in str(e)
print("5. flash_attention cross-length raises clearly")

# 6. device_value accessor
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu import models
m = models.LeNet(); m.eval()
td = tempfile.mkdtemp(); p = os.path.join(td, "m")
save(m, p, input_spec=[InputSpec([1,1,28,28],"float32")], precision="bfloat16")
pred = create_predictor(Config(p))
pred.get_input_handle(pred.get_input_names()[0]).copy_from_cpu(rng.rand(1,1,28,28).astype("float32"))
pred.run()
dv = pred.get_output_handle(pred.get_output_names()[0]).device_value()
assert dv.dtype == jnp.bfloat16 and dv.shape == (1, 10)
print("6. device_value zero-copy accessor OK")
print("ALL VERIFY DRIVES PASSED")
