import time, statistics, sys
import numpy as np
import jax, jax.numpy as jnp
PEAK = 1.97e14; B = 128; N = 300

def scan_bench(make_op, x, n=N):
    # carry scalar feeds input multiplicatively -> not constant-foldable
    @jax.jit
    def f(x):
        def body(c, _):
            o = make_op(x * (1.0 + c).astype(x.dtype))
            return o.reshape(-1)[0].astype(jnp.float32) * 1e-20, None
        c, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), None, length=n)
        return c
    r = f(x); r.block_until_ready()
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); float(np.asarray(f(x))); ts.append(time.perf_counter() - t0)
    return statistics.median(ts) / n

w = jnp.zeros((64, 3, 7, 7), jnp.bfloat16)
x = jnp.zeros((B, 3, 224, 224), jnp.bfloat16)
dt = scan_bench(lambda a: jax.lax.conv_general_dilated(
    a, w, (2, 2), [(3, 3), (3, 3)], dimension_numbers=("NCHW", "OIHW", "NCHW")), x)
flops = 2 * B * 112 * 112 * 64 * 3 * 49
print(f"stem 7x7s2 NCHW: {dt*1e3:.3f} ms  mfu={flops/dt/PEAK:.3f}")

ws = jnp.zeros((64, 12, 4, 4), jnp.bfloat16)
xs = jnp.zeros((B, 12, 112, 112), jnp.bfloat16)
dt = scan_bench(lambda a: jax.lax.conv_general_dilated(
    a, ws, (1, 1), [(2, 1), (2, 1)], dimension_numbers=("NCHW", "OIHW", "NCHW")), xs)
flops2 = 2 * B * 112 * 112 * 64 * 12 * 16
print(f"stem s2d 4x4s1:  {dt*1e3:.3f} ms  mfu={flops2/dt/PEAK:.3f}")

xp = jnp.zeros((B, 64, 112, 112), jnp.bfloat16)
dt = scan_bench(lambda a: jax.lax.reduce_window(
    a, jnp.float32(-1e30).astype(jnp.bfloat16), jax.lax.max, (1, 1, 3, 3),
    (1, 1, 2, 2), [(0, 0), (0, 0), (1, 1), (1, 1)]), xp)
print(f"maxpool NCHW:    {dt*1e3:.3f} ms")

xpl = jnp.zeros((B, 112, 112, 64), jnp.bfloat16)
dt = scan_bench(lambda a: jax.lax.reduce_window(
    a, jnp.float32(-1e30).astype(jnp.bfloat16), jax.lax.max, (1, 3, 3, 1),
    (1, 2, 2, 1), [(0, 0), (1, 1), (1, 1), (0, 0)]), xpl)
print(f"maxpool NHWC:    {dt*1e3:.3f} ms")

# representative bottleneck 3x3 at each stage
for cin, hw in ((64, 56), (128, 28), (256, 14), (512, 7)):
    wk = jnp.zeros((cin, cin, 3, 3), jnp.bfloat16)
    xk = jnp.zeros((B, cin, hw, hw), jnp.bfloat16)
    dt = scan_bench(lambda a, wk=wk: jax.lax.conv_general_dilated(
        a, wk, (1, 1), [(1, 1), (1, 1)], dimension_numbers=("NCHW", "OIHW", "NCHW")), xk)
    fl = 2 * B * hw * hw * cin * cin * 9
    print(f"3x3 c{cin:3d} hw{hw:3d}: {dt*1e3:.3f} ms  mfu={fl/dt/PEAK:.3f}")
# 1x1 convs
for cin, cout, hw in ((64, 64, 56), (256, 64, 56), (1024, 256, 14), (512, 2048, 7)):
    wk = jnp.zeros((cout, cin, 1, 1), jnp.bfloat16)
    xk = jnp.zeros((B, cin, hw, hw), jnp.bfloat16)
    dt = scan_bench(lambda a, wk=wk: jax.lax.conv_general_dilated(
        a, wk, (1, 1), [(0, 0), (0, 0)], dimension_numbers=("NCHW", "OIHW", "NCHW")), xk)
    fl = 2 * B * hw * hw * cin * cout
    print(f"1x1 c{cin:4d}->{cout:4d} hw{hw:3d}: {dt*1e3:.3f} ms  mfu={fl/dt/PEAK:.3f}")
