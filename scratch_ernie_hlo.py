import sys
import numpy as np
sys.path.insert(0, ".")
import jax
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import models
from paddle_tpu.jit import TrainStep

batch, seqlen = 32, 128
paddle.seed(0)
base = models.ernie_base(hidden_dropout_prob=0.0)
net = models.ErnieForPretraining(base)
ce = nn.CrossEntropyLoss()
def loss_fn(logits, nsp_logits, ids, nsp):
    v = logits.shape[-1]
    return ce(logits.reshape([-1, v]), ids.reshape([-1])) + ce(nsp_logits, nsp)
opt = paddle.optimizer.AdamW(parameters=net.parameters(), learning_rate=1e-4)
step = TrainStep(net, loss_fn, opt, amp_dtype="bfloat16", n_model_inputs=1)
vocab = base.embeddings.word_embeddings.weight.shape[0]
n_steps = 20
ids_all = paddle.to_tensor(np.random.randint(0, vocab, (n_steps, batch, seqlen)).astype(np.int32))
nsp_all = paddle.to_tensor(np.random.randint(0, 2, (n_steps, batch)).astype(np.int32))
step._prepare((ids_all, ids_all, nsp_all))
params = [t._value for t in step._ptensors]
buffers = [t._value for t in step._btensors]
lowered = jax.jit(step._jitted_scan.__wrapped__ if hasattr(step._jitted_scan, "__wrapped__") else None)
# use the jitted object directly
txt = step._jitted_scan.lower(params, step._slots, buffers, step._key, step._lr_arr,
                              step._t_arr, [ids_all._value], [ids_all._value, nsp_all._value]).compile().as_text()
open("_trace/ernie.hlo", "w").write(txt)
print("hlo size", len(txt))
