"""USER drive: new bench workload wiring."""
import os, sys, tempfile
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import models
from paddle_tpu.jit import InputSpec, save, TrainStep
from paddle_tpu.inference import Config, create_predictor

rng = np.random.RandomState(0)

# 1. YOLOE NHWC == NCHW with shared weights
paddle.seed(0); a = models.ppyoloe_s()
paddle.seed(0); b = models.ppyoloe_s(data_format="NHWC")
b.set_state_dict(a.state_dict())
a.eval(); b.eval()
x = rng.rand(1, 3, 64, 64).astype("float32")
ya = a(paddle.to_tensor(x))
yb = b(paddle.to_tensor(x.transpose(0, 2, 3, 1)))
for oa, ob in zip(ya, yb):
    d = np.abs(np.asarray(oa._value) - np.asarray(ob._value).transpose(0, 3, 1, 2)).max()
    assert d < 1e-4, d
print("1. YOLOE NHWC equivalence OK")

# 2. Predictor lazy casts: bf16 artifact -> copy_to_cpu returns fp32; direct run returns fp32 tensors
net = models.LeNet(); net.eval()
td = tempfile.mkdtemp(); p = os.path.join(td, "m")
save(net, p, input_spec=[InputSpec([2,1,28,28],"float32")], precision="bfloat16")
pred = create_predictor(Config(p))
xi = rng.rand(2,1,28,28).astype("float32")
pred.get_input_handle(pred.get_input_names()[0]).copy_from_cpu(xi)
pred.run()
out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
assert out.dtype == np.float32, out.dtype
outs = pred.run([paddle.to_tensor(xi).astype("bfloat16")])
assert str(outs[0].dtype).endswith("float32"), outs[0].dtype
print("2. lazy fp32 output casts OK")

# 3. SDPA threshold change: seq 1024 on CPU must NOT take flash (no tpu device) and still be correct
from paddle_tpu.nn.functional import scaled_dot_product_attention as sdpa
q = paddle.to_tensor(rng.rand(1, 1024, 2, 64).astype("float32") - 0.5)
out = sdpa(q, q, q, is_causal=True)
assert tuple(out.shape) == (1, 1024, 2, 64) and np.isfinite(np.asarray(out._value)).all()
print("3. SDPA seq-1024 CPU fallback OK")

# 4. titan-geometry layer (tiny h for CPU) through TrainStep descends
from paddle_tpu.models.ernie import ErnieLayer
class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.l = ErnieLayer(64, 4, 256, dropout=0.0)
    def forward(self, x):
        return self.l(x)
net = Block()
opt = paddle.optimizer.AdamW(parameters=net.parameters(), learning_rate=1e-3)
step = TrainStep(net, lambda o, t: ((o - t) ** 2).mean(), opt,
                 amp_dtype="bfloat16", n_model_inputs=1)
xb = paddle.to_tensor(rng.rand(3, 2, 16, 64).astype("float32"))
yb2 = paddle.to_tensor(np.zeros((3, 2, 16, 64), "float32"))
losses = step.run(xb, yb2)
lv = np.asarray(losses._value)
assert np.isfinite(lv).all() and lv[-1] < lv[0]
print("4. titan-layer TrainStep.run descends", lv.round(4))

# 5. allreduce harness end-to-end (subprocess)
sys.path.insert(0, "/root/repo")
import bench
r = bench.bench_allreduce("cpu")
assert "bus_gbps" in r and r["n_devices"] == 8, r
print("5. allreduce harness OK", r["bus_gbps"], "GB/s")
print("ALL VERIFY DRIVES PASSED")
