// Native parameter-server data plane.
//
// Reference parity: paddle/fluid/distributed/ps/service/brpc_ps_server.cc
// (pull/push dense+sparse RPC handlers) + ps/table/common_sparse_table.cc /
// common_dense_table.cc (SGD tables). Speaks EXACTLY the wire protocol of
// the python plane (distributed/ps/service.py): header `<B16sqq`
// (cmd, 16-byte table name, n, dim), one status byte per response, error
// frames as 0x00 + i64 len + message. A cluster can therefore mix python
// and native servers freely; the python PsClient drives both.
//
// Commands: 1 PULL_SPARSE  ids[n]u64            -> rows[n*dim]f32
//           2 PUSH_SPARSE  ids[n]u64 g[n*dim]   -> ok        (w -= lr*g)
//           3 PULL_DENSE                        -> i64 size, i64 shard_lo,
//                                                  i64 total, w[size]f32
//           4 PUSH_DENSE   g[n]f32              -> ok        (w -= lr*g)
//           5 STOP                              -> ok, server exits
//           6 BARRIER      n participants       -> ok once n arrived
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint8_t kPullSparse = 1, kPushSparse = 2, kPullDense = 3,
                  kPushDense = 4, kStop = 5, kBarrier = 6;
constexpr int64_t kMaxRows = 1LL << 24;
constexpr int64_t kMaxDim = 1LL << 16;
constexpr int64_t kMaxElems = 1LL << 28;

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_err(int fd, const std::string& msg) {
  uint8_t st = 0;
  int64_t len = static_cast<int64_t>(msg.size());
  return write_full(fd, &st, 1) && write_full(fd, &len, 8) &&
         write_full(fd, msg.data(), msg.size());
}

// splitmix64 -> two uniforms -> Box-Muller normal; deterministic per
// (seed, id, j) so a row re-pulled after eviction re-initializes equal
double hash_unit(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  x = x ^ (x >> 31);
  return (static_cast<double>(x >> 11) + 0.5) * (1.0 / 9007199254740992.0);
}

float init_normal(uint64_t seed, uint64_t id, uint64_t j, float std) {
  double u1 = hash_unit(seed * 0x100000001b3ULL + id * 1315423911ULL + 2 * j);
  double u2 =
      hash_unit(seed * 0xcbf29ce484222325ULL + id * 2654435761ULL + 2 * j + 1);
  double z = std::sqrt(-2.0 * std::log(u1)) * std::cos(6.283185307179586 * u2);
  return static_cast<float>(z * std);
}

struct SparseTable {
  int64_t dim;
  float lr;
  float init_std;
  uint64_t seed;
  std::mutex mu;
  std::unordered_map<int64_t, std::vector<float>> rows;

  std::vector<float>& row(int64_t id) {
    auto it = rows.find(id);
    if (it != rows.end()) return it->second;
    std::vector<float> r(static_cast<size_t>(dim));
    for (int64_t j = 0; j < dim; ++j)
      r[static_cast<size_t>(j)] = init_normal(seed, static_cast<uint64_t>(id),
                                              static_cast<uint64_t>(j),
                                              init_std);
    return rows.emplace(id, std::move(r)).first->second;
  }
};

struct DenseTable {
  float lr;
  int64_t shard_lo = 0;
  int64_t total = 0;
  std::mutex mu;
  std::vector<float> w;
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::mutex tables_mu;
  std::map<std::string, std::unique_ptr<SparseTable>> sparse;
  std::map<std::string, std::unique_ptr<DenseTable>> dense;
  // live-connection registry so stop() can unblock and drain handlers
  // before the Server is freed (no use-after-free on teardown)
  std::mutex conn_mu;
  std::condition_variable conn_cv;
  std::map<int, bool> conns;  // fd -> active
  // generation-counted barrier (python _barrier parity)
  std::mutex bar_mu;
  std::condition_variable bar_cv;
  int64_t bar_arrived = 0;
  int64_t bar_gen = 0;

  bool barrier(int64_t n) {
    std::unique_lock<std::mutex> lk(bar_mu);
    int64_t gen = bar_gen;
    if (++bar_arrived >= (n < 1 ? 1 : n)) {
      bar_arrived = 0;
      ++bar_gen;
      bar_cv.notify_all();
      return true;
    }
    bool ok = bar_cv.wait_for(lk, std::chrono::seconds(60),
                              [&] { return bar_gen != gen || stop; });
    if (!ok || (stop && bar_gen == gen)) {
      if (bar_gen == gen) --bar_arrived;
      return false;
    }
    return true;
  }
};

void handle_conn(Server* s, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  {
    std::lock_guard<std::mutex> lk(s->conn_mu);
    if (s->stop) {
      ::close(fd);
      return;
    }
    s->conns[fd] = true;
  }
  for (;;) {
    uint8_t hdr[33];
    if (!read_full(fd, hdr, sizeof(hdr))) break;
    uint8_t cmd = hdr[0];
    char namebuf[17] = {0};
    std::memcpy(namebuf, hdr + 1, 16);
    std::string name(namebuf);  // NUL-trimmed
    int64_t n, dim;
    std::memcpy(&n, hdr + 17, 8);
    std::memcpy(&dim, hdr + 25, 8);
    if (n < 0 || n > kMaxRows || dim < 0 || dim > kMaxDim ||
        n * (dim > 1 ? dim : 1) > kMaxElems) {
      send_err(fd, "ps: implausible header n=" + std::to_string(n) +
                       " dim=" + std::to_string(dim));
      break;
    }
    // read the FULL payload before acting so error replies keep the
    // stream in sync (python server does the same)
    std::vector<int64_t> ids;
    std::vector<float> payload;
    if (cmd == kPullSparse || cmd == kPushSparse) {
      ids.resize(static_cast<size_t>(n));
      if (!read_full(fd, ids.data(), static_cast<size_t>(n) * 8)) break;
    }
    if (cmd == kPushSparse) {
      payload.resize(static_cast<size_t>(n * dim));
      if (!read_full(fd, payload.data(), payload.size() * 4)) break;
    } else if (cmd == kPushDense) {
      payload.resize(static_cast<size_t>(n));
      if (!read_full(fd, payload.data(), payload.size() * 4)) break;
    }

    if (cmd == kStop) {
      uint8_t ok = 1;
      write_full(fd, &ok, 1);
      s->stop = true;
      // poke the accept loop
      ::shutdown(s->listen_fd, SHUT_RDWR);
      break;
    }
    if (cmd == kBarrier) {
      if (s->barrier(n)) {
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1)) break;
      } else {
        send_err(fd, "barrier timed out after 60s (" + std::to_string(n) +
                         " participants expected)");
      }
      continue;
    }

    SparseTable* st = nullptr;
    DenseTable* dt = nullptr;
    {
      std::lock_guard<std::mutex> lk(s->tables_mu);
      auto si = s->sparse.find(name);
      if (si != s->sparse.end()) st = si->second.get();
      auto di = s->dense.find(name);
      if (di != s->dense.end()) dt = di->second.get();
    }
    if (cmd == kPullSparse || cmd == kPushSparse) {
      if (!st) {
        if (!send_err(fd, "ps: unknown table '" + name + "'")) break;
        continue;
      }
      if (cmd == kPullSparse) {
        std::vector<float> out(static_cast<size_t>(n) *
                               static_cast<size_t>(st->dim));
        {
          std::lock_guard<std::mutex> lk(st->mu);
          for (int64_t i = 0; i < n; ++i) {
            auto& r = st->row(ids[static_cast<size_t>(i)]);
            std::memcpy(out.data() + i * st->dim, r.data(),
                        static_cast<size_t>(st->dim) * 4);
          }
        }
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1) ||
            !write_full(fd, out.data(), out.size() * 4))
          break;
      } else {
        if (dim != st->dim) {
          if (!send_err(fd, "ps: push dim mismatch")) break;
          continue;
        }
        {
          std::lock_guard<std::mutex> lk(st->mu);
          for (int64_t i = 0; i < n; ++i) {
            auto& r = st->row(ids[static_cast<size_t>(i)]);
            const float* g = payload.data() + i * dim;
            for (int64_t j = 0; j < dim; ++j)
              r[static_cast<size_t>(j)] -= st->lr * g[j];
          }
        }
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1)) break;
      }
      continue;
    }
    if (cmd == kPullDense || cmd == kPushDense) {
      if (!dt) {
        if (!send_err(fd, "ps: unknown table '" + name + "'")) break;
        continue;
      }
      if (cmd == kPullDense) {
        std::lock_guard<std::mutex> lk(dt->mu);
        uint8_t ok = 1;
        int64_t size = static_cast<int64_t>(dt->w.size());
        if (!write_full(fd, &ok, 1) || !write_full(fd, &size, 8) ||
            !write_full(fd, &dt->shard_lo, 8) ||
            !write_full(fd, &dt->total, 8) ||
            !write_full(fd, dt->w.data(), dt->w.size() * 4))
          break;
      } else {
        if (n != static_cast<int64_t>(dt->w.size())) {
          if (!send_err(fd, "ps: dense grad size mismatch")) break;
          continue;
        }
        {
          std::lock_guard<std::mutex> lk(dt->mu);
          for (int64_t i = 0; i < n; ++i)
            dt->w[static_cast<size_t>(i)] -= dt->lr * payload[i];
        }
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1)) break;
      }
      continue;
    }
    send_err(fd, "ps: unknown cmd " + std::to_string(cmd));
    break;
  }
  ::close(fd);
  {
    std::lock_guard<std::mutex> lk(s->conn_mu);
    s->conns.erase(fd);
  }
  s->conn_cv.notify_all();
}

}  // namespace

extern "C" {

void* ps_native_server_start(int port, int* out_port) {
  auto* s = new Server();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
          0 ||
      ::listen(s->listen_fd, 64) < 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  s->port = ntohs(addr.sin_port);
  if (out_port) *out_port = s->port;
  s->accept_thread = std::thread([s] {
    while (!s->stop) {
      int fd = ::accept(s->listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (s->stop) break;
        // EMFILE & friends: back off instead of spinning a core
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
        continue;
      }
      std::thread(handle_conn, s, fd).detach();
    }
  });
  return s;
}

int ps_native_add_sparse(void* h, const char* name, long long dim, float lr,
                         float init_std, long long seed) {
  auto* s = static_cast<Server*>(h);
  if (!s || !name || std::strlen(name) > 16 || dim <= 0) return -1;
  auto t = std::make_unique<SparseTable>();
  t->dim = dim;
  t->lr = lr;
  t->init_std = init_std;
  t->seed = static_cast<uint64_t>(seed);
  std::lock_guard<std::mutex> lk(s->tables_mu);
  // re-registration would free a table in-flight handlers may still hold
  if (s->sparse.count(name) || s->dense.count(name)) return -2;
  s->sparse[name] = std::move(t);
  return 0;
}

int ps_native_add_dense(void* h, const char* name, long long size, float lr,
                        long long shard_lo, long long total) {
  auto* s = static_cast<Server*>(h);
  if (!s || !name || std::strlen(name) > 16 || size < 0) return -1;
  auto t = std::make_unique<DenseTable>();
  t->lr = lr;
  t->shard_lo = shard_lo;
  t->total = total > 0 ? total : size;
  t->w.assign(static_cast<size_t>(size), 0.0f);
  std::lock_guard<std::mutex> lk(s->tables_mu);
  if (s->sparse.count(name) || s->dense.count(name)) return -2;
  s->dense[name] = std::move(t);
  return 0;
}

int ps_native_server_port(void* h) {
  auto* s = static_cast<Server*>(h);
  return s ? s->port : -1;
}

void ps_native_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  if (!s) return;
  s->stop = true;
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {  // wake barrier waiters so their handlers can exit
    std::lock_guard<std::mutex> lk(s->bar_mu);
    s->bar_cv.notify_all();
  }
  std::unique_lock<std::mutex> lk(s->conn_mu);
  for (auto& kv : s->conns) ::shutdown(kv.first, SHUT_RDWR);
  bool drained = s->conn_cv.wait_for(lk, std::chrono::seconds(5),
                                     [&] { return s->conns.empty(); });
  lk.unlock();
  if (!drained) return;  // leak rather than free under a live handler
  delete s;
}

}  // extern "C"
