// Native parameter-server data plane.
//
// Reference parity: paddle/fluid/distributed/ps/service/brpc_ps_server.cc
// (pull/push dense+sparse RPC handlers) + ps/table/common_sparse_table.cc /
// common_dense_table.cc (SGD tables). Speaks EXACTLY the wire protocol of
// the python plane (distributed/ps/service.py): header `<B16sqq`
// (cmd, 16-byte table name, n, dim), one status byte per response, error
// frames as 0x00 + i64 len + message. A cluster can therefore mix python
// and native servers freely; the python PsClient drives both.
//
// Commands: 1 PULL_SPARSE  ids[n]u64            -> rows[n*dim]f32
//           2 PUSH_SPARSE  ids[n]u64 g[n*dim]   -> ok  (table's optimizer)
//           3 PULL_DENSE                        -> i64 size, i64 shard_lo,
//                                                  i64 total, w[size]f32
//           4 PUSH_DENSE   g[n]f32              -> ok  (table's optimizer)
//           5 STOP                              -> ok, server exits
//           6 BARRIER      n participants       -> ok once n arrived
//           7 PUSH_SHOW_CLICK ids[n]u64 shows[n]f32 clicks[n]f32 -> ok
//             (CTR accessor statistics, ctr_accessor.cc UpdateShowClick)
//           8 DECAY                             -> ok  (daily time decay)
//           9 SHRINK                            -> ok, i64 evicted
//          10 ADD_SPARSE   cfg (table-config negotiation: f32 lr, f32
//             init_std, i64 seed, u8 opt{0 sgd,1 adagrad,2 adam}, u8
//             has_ctr, f32 b1, f32 b2, f32 eps, f32 show_decay, f32
//             click_coeff, f32 del_thresh, f32 ttl_days) -> ok
//          11 ADD_DENSE    cfg (f32 lr, i64 shard_lo, i64 total, u8 opt,
//             f32 b1, f32 b2, f32 eps)          -> ok
// Optimizer numerics mirror the python tier's _SGDRule/_AdagradRule/
// _AdamRule (distributed/ps/table.py) so mixed clusters converge equally.
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint8_t kPullSparse = 1, kPushSparse = 2, kPullDense = 3,
                  kPushDense = 4, kStop = 5, kBarrier = 6,
                  kPushShowClick = 7, kDecay = 8, kShrink = 9,
                  kAddSparse = 10, kAddDense = 11;
constexpr uint8_t kOptSgd = 0, kOptAdagrad = 1, kOptAdam = 2;
constexpr int64_t kMaxRows = 1LL << 24;
constexpr int64_t kMaxDim = 1LL << 16;
constexpr int64_t kMaxElems = 1LL << 28;

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_err(int fd, const std::string& msg) {
  uint8_t st = 0;
  int64_t len = static_cast<int64_t>(msg.size());
  return write_full(fd, &st, 1) && write_full(fd, &len, 8) &&
         write_full(fd, msg.data(), msg.size());
}

// splitmix64 -> two uniforms -> Box-Muller normal; deterministic per
// (seed, id, j) so a row re-pulled after eviction re-initializes equal
double hash_unit(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  x = x ^ (x >> 31);
  return (static_cast<double>(x >> 11) + 0.5) * (1.0 / 9007199254740992.0);
}

float init_normal(uint64_t seed, uint64_t id, uint64_t j, float std) {
  double u1 = hash_unit(seed * 0x100000001b3ULL + id * 1315423911ULL + 2 * j);
  double u2 =
      hash_unit(seed * 0xcbf29ce484222325ULL + id * 2654435761ULL + 2 * j + 1);
  double z = std::sqrt(-2.0 * std::log(u1)) * std::cos(6.283185307179586 * u2);
  return static_cast<float>(z * std);
}

struct OptCfg {
  uint8_t type = kOptSgd;
  float b1 = 0.9f, b2 = 0.999f, eps = 1e-8f;
};

struct CtrCfg {
  bool enabled = false;
  float show_decay = 0.98f, click_coeff = 8.0f;
  float del_thresh = 0.8f, ttl_days = 30.0f;
};

struct SparseRow {
  std::vector<float> w;
  std::vector<float> s1;  // adagrad g2 / adam m
  std::vector<float> s2;  // adam v
  float t = 0.0f;         // adam per-row step (lazy adam contract)
  float show = 0.0f, click = 0.0f, unseen = 0.0f;  // ctr stats
};

struct SparseTable {
  int64_t dim;
  float lr;
  float init_std;
  uint64_t seed;
  OptCfg opt;
  CtrCfg ctr;
  std::mutex mu;
  std::unordered_map<int64_t, SparseRow> rows;

  SparseRow& row(int64_t id) {
    auto it = rows.find(id);
    if (it != rows.end()) return it->second;
    SparseRow r;
    r.w.resize(static_cast<size_t>(dim));
    for (int64_t j = 0; j < dim; ++j)
      r.w[static_cast<size_t>(j)] = init_normal(
          seed, static_cast<uint64_t>(id), static_cast<uint64_t>(j), init_std);
    if (opt.type == kOptAdagrad) r.s1.assign(static_cast<size_t>(dim), 0.0f);
    if (opt.type == kOptAdam) {
      r.s1.assign(static_cast<size_t>(dim), 0.0f);
      r.s2.assign(static_cast<size_t>(dim), 0.0f);
    }
    return rows.emplace(id, std::move(r)).first->second;
  }

  // python _RULES numerics (table.py): float32 arithmetic throughout
  void apply(SparseRow& r, const float* g) {
    switch (opt.type) {
      case kOptAdagrad:
        for (int64_t j = 0; j < dim; ++j) {
          size_t k = static_cast<size_t>(j);
          r.s1[k] += g[j] * g[j];
          r.w[k] -= lr * g[j] / (std::sqrt(r.s1[k]) + opt.eps);
        }
        break;
      case kOptAdam: {
        r.t += 1.0f;
        float bc1 = 1.0f - std::pow(opt.b1, r.t);
        float bc2 = 1.0f - std::pow(opt.b2, r.t);
        for (int64_t j = 0; j < dim; ++j) {
          size_t k = static_cast<size_t>(j);
          r.s1[k] = opt.b1 * r.s1[k] + (1.0f - opt.b1) * g[j];
          r.s2[k] = opt.b2 * r.s2[k] + (1.0f - opt.b2) * g[j] * g[j];
          float mhat = r.s1[k] / bc1;
          float vhat = r.s2[k] / bc2;
          r.w[k] -= lr * mhat / (std::sqrt(vhat) + opt.eps);
        }
        break;
      }
      default:
        for (int64_t j = 0; j < dim; ++j)
          r.w[static_cast<size_t>(j)] -= lr * g[j];
    }
  }
};

struct DenseTable {
  float lr;
  int64_t shard_lo = 0;
  int64_t total = 0;
  OptCfg opt;
  float t = 0.0f;
  std::mutex mu;
  std::vector<float> w, s1, s2;

  void ensure_slots() {
    if (opt.type == kOptAdagrad && s1.size() != w.size())
      s1.assign(w.size(), 0.0f);
    if (opt.type == kOptAdam && s1.size() != w.size()) {
      s1.assign(w.size(), 0.0f);
      s2.assign(w.size(), 0.0f);
    }
  }

  void apply(const float* g, int64_t n) {
    ensure_slots();
    switch (opt.type) {
      case kOptAdagrad:
        for (int64_t i = 0; i < n; ++i) {
          size_t k = static_cast<size_t>(i);
          s1[k] += g[i] * g[i];
          w[k] -= lr * g[i] / (std::sqrt(s1[k]) + opt.eps);
        }
        break;
      case kOptAdam: {
        t += 1.0f;
        float bc1 = 1.0f - std::pow(opt.b1, t);
        float bc2 = 1.0f - std::pow(opt.b2, t);
        for (int64_t i = 0; i < n; ++i) {
          size_t k = static_cast<size_t>(i);
          s1[k] = opt.b1 * s1[k] + (1.0f - opt.b1) * g[i];
          s2[k] = opt.b2 * s2[k] + (1.0f - opt.b2) * g[i] * g[i];
          w[k] -= lr * (s1[k] / bc1) / (std::sqrt(s2[k] / bc2) + opt.eps);
        }
        break;
      }
      default:
        for (int64_t i = 0; i < n; ++i) w[static_cast<size_t>(i)] -= lr * g[i];
    }
  }
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::mutex tables_mu;
  std::map<std::string, std::unique_ptr<SparseTable>> sparse;
  std::map<std::string, std::unique_ptr<DenseTable>> dense;
  // live-connection registry so stop() can unblock and drain handlers
  // before the Server is freed (no use-after-free on teardown)
  std::mutex conn_mu;
  std::condition_variable conn_cv;
  std::map<int, bool> conns;  // fd -> active
  // generation-counted barrier (python _barrier parity)
  std::mutex bar_mu;
  std::condition_variable bar_cv;
  int64_t bar_arrived = 0;
  int64_t bar_gen = 0;

  bool barrier(int64_t n) {
    std::unique_lock<std::mutex> lk(bar_mu);
    int64_t gen = bar_gen;
    if (++bar_arrived >= (n < 1 ? 1 : n)) {
      bar_arrived = 0;
      ++bar_gen;
      bar_cv.notify_all();
      return true;
    }
    bool ok = bar_cv.wait_for(lk, std::chrono::seconds(60),
                              [&] { return bar_gen != gen || stop; });
    if (!ok || (stop && bar_gen == gen)) {
      if (bar_gen == gen) --bar_arrived;
      return false;
    }
    return true;
  }
};

void handle_conn(Server* s, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  {
    std::lock_guard<std::mutex> lk(s->conn_mu);
    if (s->stop) {
      ::close(fd);
      return;
    }
    s->conns[fd] = true;
  }
  for (;;) {
    uint8_t hdr[33];
    if (!read_full(fd, hdr, sizeof(hdr))) break;
    uint8_t cmd = hdr[0];
    char namebuf[17] = {0};
    std::memcpy(namebuf, hdr + 1, 16);
    std::string name(namebuf);  // NUL-trimmed
    int64_t n, dim;
    std::memcpy(&n, hdr + 17, 8);
    std::memcpy(&dim, hdr + 25, 8);
    if (n < 0 || n > kMaxRows || dim < 0 || dim > kMaxDim ||
        n * (dim > 1 ? dim : 1) > kMaxElems) {
      send_err(fd, "ps: implausible header n=" + std::to_string(n) +
                       " dim=" + std::to_string(dim));
      break;
    }
    // read the FULL payload before acting so error replies keep the
    // stream in sync (python server does the same)
    std::vector<int64_t> ids;
    std::vector<float> payload;
    if (cmd == kPullSparse || cmd == kPushSparse || cmd == kPushShowClick) {
      ids.resize(static_cast<size_t>(n));
      if (!read_full(fd, ids.data(), static_cast<size_t>(n) * 8)) break;
    }
    if (cmd == kPushSparse) {
      payload.resize(static_cast<size_t>(n * dim));
      if (!read_full(fd, payload.data(), payload.size() * 4)) break;
    } else if (cmd == kPushDense) {
      payload.resize(static_cast<size_t>(n));
      if (!read_full(fd, payload.data(), payload.size() * 4)) break;
    } else if (cmd == kPushShowClick) {
      payload.resize(static_cast<size_t>(n) * 2);  // shows then clicks
      if (!read_full(fd, payload.data(), payload.size() * 4)) break;
    }
    // table-config negotiation frames (fixed-size config payloads)
    if (cmd == kAddSparse) {
      uint8_t cfg[46];  // lr,std f32 | seed i64 | opt,ctr u8 | 7x f32
      if (!read_full(fd, cfg, sizeof(cfg))) break;
      float lr, istd, b1, b2, eps, sdec, ccoef, dth, ttl;
      int64_t seed;
      std::memcpy(&lr, cfg + 0, 4);
      std::memcpy(&istd, cfg + 4, 4);
      std::memcpy(&seed, cfg + 8, 8);
      uint8_t optid = cfg[16], hasctr = cfg[17];
      std::memcpy(&b1, cfg + 18, 4);
      std::memcpy(&b2, cfg + 22, 4);
      std::memcpy(&eps, cfg + 26, 4);
      std::memcpy(&sdec, cfg + 30, 4);
      std::memcpy(&ccoef, cfg + 34, 4);
      std::memcpy(&dth, cfg + 38, 4);
      std::memcpy(&ttl, cfg + 42, 4);
      if (optid > kOptAdam || dim <= 0) {
        if (!send_err(fd, "ps: bad sparse table config")) break;
        continue;
      }
      auto t = std::make_unique<SparseTable>();
      t->dim = dim;
      t->lr = lr;
      t->init_std = istd;
      t->seed = static_cast<uint64_t>(seed);
      t->opt.type = optid;
      t->opt.b1 = b1;
      t->opt.b2 = b2;
      t->opt.eps = eps;
      t->ctr.enabled = hasctr != 0;
      t->ctr.show_decay = sdec;
      t->ctr.click_coeff = ccoef;
      t->ctr.del_thresh = dth;
      t->ctr.ttl_days = ttl;
      {
        std::lock_guard<std::mutex> lk(s->tables_mu);
        if (s->sparse.count(name) || s->dense.count(name)) {
          if (!send_err(fd, "ps: table '" + name + "' already registered"))
            break;
          continue;
        }
        s->sparse[name] = std::move(t);
      }
      uint8_t ok = 1;
      if (!write_full(fd, &ok, 1)) break;
      continue;
    }
    if (cmd == kAddDense) {
      uint8_t cfg[33];  // lr f32 | shard_lo,total i64 | opt u8 | b1,b2,eps
      if (!read_full(fd, cfg, sizeof(cfg))) break;
      float lr, b1, b2, eps;
      int64_t lo, total;
      std::memcpy(&lr, cfg + 0, 4);
      std::memcpy(&lo, cfg + 4, 8);
      std::memcpy(&total, cfg + 12, 8);
      uint8_t optid = cfg[20];
      std::memcpy(&b1, cfg + 21, 4);
      std::memcpy(&b2, cfg + 25, 4);
      std::memcpy(&eps, cfg + 29, 4);
      if (optid > kOptAdam || n < 0) {
        if (!send_err(fd, "ps: bad dense table config")) break;
        continue;
      }
      auto t = std::make_unique<DenseTable>();
      t->lr = lr;
      t->shard_lo = lo;
      t->total = total > 0 ? total : n;
      t->opt.type = optid;
      t->opt.b1 = b1;
      t->opt.b2 = b2;
      t->opt.eps = eps;
      // zero init matches the python DenseTable default (initializer=None
      // -> zeros, table.py) so wire-negotiated mixed clusters agree
      t->w.assign(static_cast<size_t>(n), 0.0f);
      {
        std::lock_guard<std::mutex> lk(s->tables_mu);
        if (s->sparse.count(name) || s->dense.count(name)) {
          if (!send_err(fd, "ps: table '" + name + "' already registered"))
            break;
          continue;
        }
        s->dense[name] = std::move(t);
      }
      uint8_t ok = 1;
      if (!write_full(fd, &ok, 1)) break;
      continue;
    }

    if (cmd == kStop) {
      uint8_t ok = 1;
      write_full(fd, &ok, 1);
      s->stop = true;
      // poke the accept loop
      ::shutdown(s->listen_fd, SHUT_RDWR);
      break;
    }
    if (cmd == kBarrier) {
      if (s->barrier(n)) {
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1)) break;
      } else {
        send_err(fd, "barrier timed out after 60s (" + std::to_string(n) +
                         " participants expected)");
      }
      continue;
    }

    SparseTable* st = nullptr;
    DenseTable* dt = nullptr;
    {
      std::lock_guard<std::mutex> lk(s->tables_mu);
      auto si = s->sparse.find(name);
      if (si != s->sparse.end()) st = si->second.get();
      auto di = s->dense.find(name);
      if (di != s->dense.end()) dt = di->second.get();
    }
    if (cmd == kPullSparse || cmd == kPushSparse) {
      if (!st) {
        if (!send_err(fd, "ps: unknown table '" + name + "'")) break;
        continue;
      }
      if (cmd == kPullSparse) {
        std::vector<float> out(static_cast<size_t>(n) *
                               static_cast<size_t>(st->dim));
        {
          std::lock_guard<std::mutex> lk(st->mu);
          for (int64_t i = 0; i < n; ++i) {
            auto& r = st->row(ids[static_cast<size_t>(i)]);
            std::memcpy(out.data() + i * st->dim, r.w.data(),
                        static_cast<size_t>(st->dim) * 4);
          }
        }
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1) ||
            !write_full(fd, out.data(), out.size() * 4))
          break;
      } else {
        if (dim != st->dim) {
          if (!send_err(fd, "ps: push dim mismatch")) break;
          continue;
        }
        {
          // accumulate duplicate ids before applying — ONE optimizer step
          // per key, matching the python SparseTable.push contract (for
          // adam/adagrad a per-occurrence loop would advance the slots
          // twice and break mixed-cluster numeric parity)
          std::lock_guard<std::mutex> lk(st->mu);
          std::unordered_map<int64_t, std::vector<float>> acc;
          for (int64_t i = 0; i < n; ++i) {
            auto& a = acc[ids[static_cast<size_t>(i)]];
            const float* g = payload.data() + i * dim;
            if (a.empty())
              a.assign(g, g + dim);
            else
              for (int64_t j = 0; j < dim; ++j) a[static_cast<size_t>(j)] += g[j];
          }
          for (auto& kv : acc) {
            auto& r = st->row(kv.first);
            st->apply(r, kv.second.data());
          }
        }
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1)) break;
      }
      continue;
    }
    if (cmd == kPushShowClick || cmd == kDecay || cmd == kShrink) {
      if (!st) {
        if (!send_err(fd, "ps: unknown table '" + name + "'")) break;
        continue;
      }
      if (!st->ctr.enabled) {
        if (!send_err(fd, "ps: table '" + name + "' has no ctr accessor"))
          break;
        continue;
      }
      if (cmd == kPushShowClick) {
        // ctr_accessor.cc UpdateShowClick: bump counters, reset unseen
        std::lock_guard<std::mutex> lk(st->mu);
        const float* shows = payload.data();
        const float* clicks = payload.data() + n;
        for (int64_t i = 0; i < n; ++i) {
          auto& r = st->row(ids[static_cast<size_t>(i)]);
          r.show += shows[i];
          r.click += clicks[i];
          r.unseen = 0.0f;
        }
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1)) break;
      } else if (cmd == kDecay) {
        // UpdateTimeDecay (daily): decay counters, age rows
        std::lock_guard<std::mutex> lk(st->mu);
        for (auto& kv : st->rows) {
          kv.second.show *= st->ctr.show_decay;
          kv.second.click *= st->ctr.show_decay;
          kv.second.unseen += 1.0f;
        }
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1)) break;
      } else {
        // Table::Shrink: evict low-score / expired rows
        int64_t evicted = 0;
        {
          std::lock_guard<std::mutex> lk(st->mu);
          for (auto it = st->rows.begin(); it != st->rows.end();) {
            const auto& r = it->second;
            float score = r.show + st->ctr.click_coeff * r.click;
            if (score < st->ctr.del_thresh ||
                r.unseen > st->ctr.ttl_days) {
              it = st->rows.erase(it);
              ++evicted;
            } else {
              ++it;
            }
          }
        }
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1) || !write_full(fd, &evicted, 8)) break;
      }
      continue;
    }
    if (cmd == kPullDense || cmd == kPushDense) {
      if (!dt) {
        if (!send_err(fd, "ps: unknown table '" + name + "'")) break;
        continue;
      }
      if (cmd == kPullDense) {
        std::lock_guard<std::mutex> lk(dt->mu);
        uint8_t ok = 1;
        int64_t size = static_cast<int64_t>(dt->w.size());
        if (!write_full(fd, &ok, 1) || !write_full(fd, &size, 8) ||
            !write_full(fd, &dt->shard_lo, 8) ||
            !write_full(fd, &dt->total, 8) ||
            !write_full(fd, dt->w.data(), dt->w.size() * 4))
          break;
      } else {
        if (n != static_cast<int64_t>(dt->w.size())) {
          if (!send_err(fd, "ps: dense grad size mismatch")) break;
          continue;
        }
        {
          std::lock_guard<std::mutex> lk(dt->mu);
          dt->apply(payload.data(), n);
        }
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1)) break;
      }
      continue;
    }
    send_err(fd, "ps: unknown cmd " + std::to_string(cmd));
    break;
  }
  ::close(fd);
  {
    // erase AND notify under the lock: stop()'s wait_for could otherwise
    // observe conns.empty() between our unlock and notify, delete the
    // Server, and leave this notify_all touching a freed cv
    std::lock_guard<std::mutex> lk(s->conn_mu);
    s->conns.erase(fd);
    s->conn_cv.notify_all();
  }
}

}  // namespace

extern "C" {

void* ps_native_server_start(int port, int* out_port) {
  auto* s = new Server();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
          0 ||
      ::listen(s->listen_fd, 64) < 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  s->port = ntohs(addr.sin_port);
  if (out_port) *out_port = s->port;
  s->accept_thread = std::thread([s] {
    while (!s->stop) {
      int fd = ::accept(s->listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (s->stop) break;
        // EMFILE & friends: back off instead of spinning a core
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
        continue;
      }
      std::thread(handle_conn, s, fd).detach();
    }
  });
  return s;
}

int ps_native_add_sparse(void* h, const char* name, long long dim, float lr,
                         float init_std, long long seed) {
  auto* s = static_cast<Server*>(h);
  if (!s || !name || std::strlen(name) > 16 || dim <= 0) return -1;
  auto t = std::make_unique<SparseTable>();
  t->dim = dim;
  t->lr = lr;
  t->init_std = init_std;
  t->seed = static_cast<uint64_t>(seed);
  std::lock_guard<std::mutex> lk(s->tables_mu);
  // re-registration would free a table in-flight handlers may still hold
  if (s->sparse.count(name) || s->dense.count(name)) return -2;
  s->sparse[name] = std::move(t);
  return 0;
}

int ps_native_add_dense(void* h, const char* name, long long size, float lr,
                        long long shard_lo, long long total) {
  auto* s = static_cast<Server*>(h);
  if (!s || !name || std::strlen(name) > 16 || size < 0) return -1;
  auto t = std::make_unique<DenseTable>();
  t->lr = lr;
  t->shard_lo = shard_lo;
  t->total = total > 0 ? total : size;
  t->w.assign(static_cast<size_t>(size), 0.0f);
  std::lock_guard<std::mutex> lk(s->tables_mu);
  if (s->sparse.count(name) || s->dense.count(name)) return -2;
  s->dense[name] = std::move(t);
  return 0;
}

int ps_native_add_sparse_v2(void* h, const char* name, long long dim,
                            float lr, float init_std, long long seed,
                            int opt_id, float b1, float b2, float eps,
                            int has_ctr, float show_decay, float click_coeff,
                            float del_thresh, float ttl_days) {
  auto* s = static_cast<Server*>(h);
  if (!s || !name || std::strlen(name) > 16 || dim <= 0 || opt_id < 0 ||
      opt_id > kOptAdam)
    return -1;
  auto t = std::make_unique<SparseTable>();
  t->dim = dim;
  t->lr = lr;
  t->init_std = init_std;
  t->seed = static_cast<uint64_t>(seed);
  t->opt.type = static_cast<uint8_t>(opt_id);
  t->opt.b1 = b1;
  t->opt.b2 = b2;
  t->opt.eps = eps;
  t->ctr.enabled = has_ctr != 0;
  t->ctr.show_decay = show_decay;
  t->ctr.click_coeff = click_coeff;
  t->ctr.del_thresh = del_thresh;
  t->ctr.ttl_days = ttl_days;
  std::lock_guard<std::mutex> lk(s->tables_mu);
  if (s->sparse.count(name) || s->dense.count(name)) return -2;
  s->sparse[name] = std::move(t);
  return 0;
}

int ps_native_add_dense_v2(void* h, const char* name, long long size,
                           float lr, long long shard_lo, long long total,
                           int opt_id, float b1, float b2, float eps) {
  auto* s = static_cast<Server*>(h);
  if (!s || !name || std::strlen(name) > 16 || size < 0 || opt_id < 0 ||
      opt_id > kOptAdam)
    return -1;
  auto t = std::make_unique<DenseTable>();
  t->lr = lr;
  t->shard_lo = shard_lo;
  t->total = total > 0 ? total : size;
  t->opt.type = static_cast<uint8_t>(opt_id);
  t->opt.b1 = b1;
  t->opt.b2 = b2;
  t->opt.eps = eps;
  t->w.assign(static_cast<size_t>(size), 0.0f);
  std::lock_guard<std::mutex> lk(s->tables_mu);
  if (s->sparse.count(name) || s->dense.count(name)) return -2;
  s->dense[name] = std::move(t);
  return 0;
}

int ps_native_server_port(void* h) {
  auto* s = static_cast<Server*>(h);
  return s ? s->port : -1;
}

void ps_native_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  if (!s) return;
  s->stop = true;
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {  // wake barrier waiters so their handlers can exit
    std::lock_guard<std::mutex> lk(s->bar_mu);
    s->bar_cv.notify_all();
  }
  std::unique_lock<std::mutex> lk(s->conn_mu);
  for (auto& kv : s->conns) ::shutdown(kv.first, SHUT_RDWR);
  bool drained = s->conn_cv.wait_for(lk, std::chrono::seconds(5),
                                     [&] { return s->conns.empty(); });
  lk.unlock();
  if (!drained) return;  // leak rather than free under a live handler
  delete s;
}

}  // extern "C"
