// Host runtime utilities: stats monitor + threadpool batch assembler +
// pinned host buffer pool.
//
// Reference parity:
//   - monitor: paddle/fluid/platform/monitor.cc (STAT_ADD int-stat registry,
//     exported to python via pybind/metrics_py.cc);
//   - batch assembler: the parallel memcpy core of
//     operators/reader/buffered_reader.cc + fluid DataLoader workers — the
//     hot host loop of data ingestion (gather N sample buffers into one
//     contiguous batch, multi-threaded);
//   - buffer pool: memory/allocation host-pinned allocator role (on TPU the
//     runtime owns device memory; the host side keeps reusable aligned
//     staging buffers to avoid malloc churn on the ingest path).
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------- stats monitor ----------------
struct Monitor {
  std::mutex mu;
  std::map<std::string, int64_t> stats;
};

Monitor& monitor() {
  static Monitor m;
  return m;
}

// ---------------- threadpool ----------------
class ThreadPool {
 public:
  explicit ThreadPool(int n) : stop_(false) {
    for (int i = 0; i < n; ++i)
      threads_.emplace_back([this] { Loop(); });
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> g(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : threads_) t.join();
  }

  void Submit(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> g(mu_);
      q_.push_back(std::move(fn));
    }
    cv_.notify_one();
  }

  void WaitAll() {
    std::unique_lock<std::mutex> g(mu_);
    done_cv_.wait(g, [this] { return q_.empty() && active_ == 0; });
  }

 private:
  void Loop() {
    for (;;) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> g(mu_);
        cv_.wait(g, [this] { return stop_ || !q_.empty(); });
        if (stop_ && q_.empty()) return;
        fn = std::move(q_.front());
        q_.pop_front();
        ++active_;
      }
      fn();
      {
        std::lock_guard<std::mutex> g(mu_);
        --active_;
        if (q_.empty() && active_ == 0) done_cv_.notify_all();
      }
    }
  }

  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
  std::deque<std::function<void()>> q_;
  std::vector<std::thread> threads_;
  int active_ = 0;
  bool stop_;
};

ThreadPool& pool() {
  static ThreadPool p(static_cast<int>(std::thread::hardware_concurrency() / 2 + 1));
  return p;
}

}  // namespace

extern "C" {

// ---- monitor (STAT_ADD parity) ----
void monitor_add(const char* name, int64_t delta) {
  auto& m = monitor();
  std::lock_guard<std::mutex> g(m.mu);
  m.stats[name] += delta;
}

int64_t monitor_get(const char* name) {
  auto& m = monitor();
  std::lock_guard<std::mutex> g(m.mu);
  auto it = m.stats.find(name);
  return it == m.stats.end() ? 0 : it->second;
}

void monitor_reset(const char* name) {
  auto& m = monitor();
  std::lock_guard<std::mutex> g(m.mu);
  if (name && *name)
    m.stats.erase(name);
  else
    m.stats.clear();
}

// snapshot names into a packed buffer "k1=v1\nk2=v2\n"; returns bytes written
int64_t monitor_dump(char* buf, int64_t cap) {
  auto& m = monitor();
  std::lock_guard<std::mutex> g(m.mu);
  std::string out;
  for (auto& kv : m.stats)
    out += kv.first + "=" + std::to_string(kv.second) + "\n";
  int64_t n = static_cast<int64_t>(out.size());
  if (n <= cap) memcpy(buf, out.data(), out.size());
  return n;
}

// ---- parallel batch assembler ----
// Copies n sample buffers (src[i], size bytes each, uniform) into dst
// contiguously using the shared threadpool. Returns 0 on success.
int batch_assemble(uint8_t* dst, const uint8_t** srcs, int64_t n,
                   int64_t sample_bytes) {
  if (n <= 0) return 0;
  const int64_t kGrain = 1 << 20;  // ~1MB per task
  int64_t per_task = sample_bytes >= kGrain ? 1 : (kGrain / (sample_bytes + 1)) + 1;
  std::atomic<int> err{0};
  for (int64_t start = 0; start < n; start += per_task) {
    int64_t end = start + per_task < n ? start + per_task : n;
    pool().Submit([=, &err] {
      for (int64_t i = start; i < end; ++i) {
        if (!srcs[i]) {
          err.store(1);
          return;
        }
        memcpy(dst + i * sample_bytes, srcs[i], static_cast<size_t>(sample_bytes));
      }
    });
  }
  pool().WaitAll();
  return err.load();
}

// ragged variant: per-sample sizes with destination offsets
int batch_assemble_ragged(uint8_t* dst, const uint8_t** srcs,
                          const int64_t* sizes, const int64_t* offsets,
                          int64_t n) {
  std::atomic<int> err{0};
  for (int64_t i = 0; i < n; ++i) {
    pool().Submit([=, &err] {
      if (!srcs[i]) {
        err.store(1);
        return;
      }
      memcpy(dst + offsets[i], srcs[i], static_cast<size_t>(sizes[i]));
    });
  }
  pool().WaitAll();
  return err.load();
}

// ---- aligned host buffer pool ----
void* host_buffer_alloc(int64_t bytes) {
  void* p = nullptr;
  if (posix_memalign(&p, 4096, static_cast<size_t>(bytes)) != 0) return nullptr;
  return p;
}

void host_buffer_free(void* p) { free(p); }

}  // extern "C"
