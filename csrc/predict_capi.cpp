// C inference API — client for the predictor service.
//
// Reference parity: paddle/fluid/inference/capi_exp/ (PD_Predictor*,
// PD_Tensor* stable C ABI for C/Go apps). The reference links the whole
// C++ predictor into the app; the TPU runtime is host-served (XLA/PJRT
// lives with the chips), so the stable ABI here is a thin binary-protocol
// client to a predictor server process (paddle_tpu.inference.server) —
// same role: C/Go programs run TPU inference with no Python dependency.
//
// Wire protocol (little-endian):
//   request:  u32 magic 'PDRQ', u32 n_tensors,
//             per tensor: u32 dtype(0=f32,1=i32,2=i64), u32 ndim,
//                         i64 dims[ndim], payload bytes
//   deadline: u32 magic 'PDRD', u32 deadline_ms, u32 n_tensors, tensors
//   response: u32 magic 'PDRS', u8 status,
//             status==0: u32 n_tensors + tensors (same encoding)
//             status!=0: u32 len + utf-8 message
//               status 1 = server-side error        -> rc 3
//               status 2 = server overloaded        -> rc 4 (retryable
//                          backpressure, NOT a failure: back off + retry)
//               status 3 = request deadline expired -> rc 5
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kReqMagic = 0x50445251;       // 'PDRQ'
constexpr uint32_t kReqDeadlineMagic = 0x50445244;  // 'PDRD'
constexpr uint32_t kRespMagic = 0x50445253;      // 'PDRS'
constexpr int kMaxNdim = 8;

// PD_PredictorRun* return codes (>=3 carry a message in PD_GetLastError)
constexpr int kOk = 0;
constexpr int kBadArgs = 1;
constexpr int kTransportError = 2;
constexpr int kServerError = 3;
constexpr int kOverloaded = 4;   // server backpressure: retry with backoff
constexpr int kDeadlineExpired = 5;

size_t dtype_size(int dt) { return dt == 0 ? 4 : dt == 1 ? 4 : 8; }

bool send_exact(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool recv_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

}  // namespace

extern "C" {

typedef struct PD_Tensor {
  int32_t dtype;  // 0=float32, 1=int32, 2=int64
  int32_t ndim;
  int64_t dims[kMaxNdim];
  void* data;  // owned by the library for outputs (PD_TensorsDestroy)
} PD_Tensor;

typedef struct PD_Predictor {
  int fd;
  std::string last_error;
} PD_Predictor;

PD_Predictor* PD_PredictorCreate(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return nullptr;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return new PD_Predictor{fd, std::string()};
}

void PD_PredictorDestroy(PD_Predictor* p) {
  if (p == nullptr) return;
  ::close(p->fd);
  delete p;
}

const char* PD_GetLastError(PD_Predictor* p) {
  return p != nullptr ? p->last_error.c_str() : "null predictor";
}

// Returns 0 on success; fills *outputs (malloc'd array of n) + *n_out.
// deadline_ms > 0 rides the 'PDRD' frame: the server drops the request
// before batching if the deadline passes in its queue (rc 5).
static int RunImpl(PD_Predictor* p, uint32_t deadline_ms,
                   const PD_Tensor* inputs, int n_in, PD_Tensor** outputs,
                   int* n_out) {
  if (p == nullptr || inputs == nullptr || outputs == nullptr ||
      n_out == nullptr || n_in <= 0)
    return kBadArgs;
  *outputs = nullptr;
  *n_out = 0;
  // validate EVERY input before the first byte goes out: an argument
  // error after the header would leave the connection desynchronized
  for (int i = 0; i < n_in; ++i) {
    const PD_Tensor& t = inputs[i];
    if (t.ndim < 0 || t.ndim > kMaxNdim || t.dtype < 0 || t.dtype > 2 ||
        t.data == nullptr) {
      p->last_error = "invalid input tensor (ndim/dtype/data)";
      return kBadArgs;
    }
    for (int d = 0; d < t.ndim; ++d)
      if (t.dims[d] < 0) {
        p->last_error = "negative input dim";
        return kBadArgs;
      }
  }
  bool sent_ok;
  if (deadline_ms > 0) {
    uint32_t hdr[3] = {kReqDeadlineMagic, deadline_ms,
                       static_cast<uint32_t>(n_in)};
    sent_ok = send_exact(p->fd, hdr, sizeof(hdr));
  } else {
    uint32_t hdr[2] = {kReqMagic, static_cast<uint32_t>(n_in)};
    sent_ok = send_exact(p->fd, hdr, sizeof(hdr));
  }
  if (!sent_ok) {
    p->last_error = "send failed (header)";
    return kTransportError;
  }
  for (int i = 0; i < n_in; ++i) {
    const PD_Tensor& t = inputs[i];
    uint32_t meta[2] = {static_cast<uint32_t>(t.dtype),
                        static_cast<uint32_t>(t.ndim)};
    size_t count = 1;
    for (int d = 0; d < t.ndim; ++d) count *= static_cast<size_t>(t.dims[d]);
    if (!send_exact(p->fd, meta, sizeof(meta)) ||
        !send_exact(p->fd, t.dims, sizeof(int64_t) * t.ndim) ||
        !send_exact(p->fd, t.data, count * dtype_size(t.dtype))) {
      p->last_error = "send failed (tensor)";
      return kTransportError;
    }
  }
  uint32_t magic = 0;
  uint8_t status = 0;
  if (!recv_exact(p->fd, &magic, 4) || magic != kRespMagic ||
      !recv_exact(p->fd, &status, 1)) {
    p->last_error = "bad response header";
    return kTransportError;
  }
  if (status != 0) {
    uint32_t len = 0;
    if (!recv_exact(p->fd, &len, 4)) return kTransportError;
    if (len > (64u << 10)) {  // cap: corrupt length must not drive alloc
      p->last_error = "implausible error-message length";
      return kTransportError;
    }
    std::vector<char> msg(len);
    if (!recv_exact(p->fd, msg.data(), len)) return kTransportError;
    p->last_error.assign(msg.data(), len);
    // the connection stays framed after any status frame: retryable
    // backpressure and deadline expiry are distinguishable from failure
    if (status == 2) return kOverloaded;
    if (status == 3) return kDeadlineExpired;
    return kServerError;  // message in PD_GetLastError
  }
  uint32_t n = 0;
  if (!recv_exact(p->fd, &n, 4)) return kTransportError;
  if (n > 1024) {  // corrupt/hostile response: don't trust the count
    p->last_error = "implausible output tensor count";
    return kTransportError;
  }
  PD_Tensor* outs =
      static_cast<PD_Tensor*>(std::calloc(n, sizeof(PD_Tensor)));
  if (outs == nullptr && n > 0) {
    p->last_error = "out of memory (outputs)";
    return kTransportError;
  }
  // one cleanup path frees every buffer received so far (calloc zeroed
  // data pointers, so free(nullptr) is safe for the rest)
  auto fail = [&](const char* msg) {
    for (uint32_t j = 0; j < n; ++j) std::free(outs[j].data);
    std::free(outs);
    p->last_error = msg;
    return kTransportError;
  };
  for (uint32_t i = 0; i < n; ++i) {
    uint32_t meta[2];
    if (!recv_exact(p->fd, meta, sizeof(meta)) ||
        meta[1] > static_cast<uint32_t>(kMaxNdim))
      return fail("bad output tensor header");
    outs[i].dtype = static_cast<int32_t>(meta[0]);
    outs[i].ndim = static_cast<int32_t>(meta[1]);
    if (!recv_exact(p->fd, outs[i].dims, sizeof(int64_t) * outs[i].ndim))
      return fail("short read (output dims)");
    // per-dim + cumulative bounds: a hostile dims pair like 2^33 x 2^33
    // must not wrap size_t past the total-size guard
    constexpr size_t kMaxElems = size_t{1} << 33;
    size_t count = 1;
    for (int d = 0; d < outs[i].ndim; ++d) {
      int64_t dim = outs[i].dims[d];
      if (dim < 0 || static_cast<size_t>(dim) > kMaxElems)
        return fail("implausible output dim");
      if (dim != 0 && count > kMaxElems / static_cast<size_t>(dim))
        return fail("implausible output tensor size");
      count *= static_cast<size_t>(dim);
    }
    size_t nbytes = count * dtype_size(outs[i].dtype);
    if (nbytes > (size_t{1} << 33))
      return fail("implausible output tensor size");
    outs[i].data = std::malloc(nbytes);
    if (outs[i].data == nullptr)
      return fail("out of memory (output payload)");
    if (!recv_exact(p->fd, outs[i].data, nbytes))
      return fail("short read (output payload)");
  }
  *outputs = outs;
  *n_out = static_cast<int>(n);
  return kOk;
}

int PD_PredictorRun(PD_Predictor* p, const PD_Tensor* inputs, int n_in,
                    PD_Tensor** outputs, int* n_out) {
  return RunImpl(p, 0, inputs, n_in, outputs, n_out);
}

int PD_PredictorRunWithDeadline(PD_Predictor* p, uint32_t deadline_ms,
                                const PD_Tensor* inputs, int n_in,
                                PD_Tensor** outputs, int* n_out) {
  return RunImpl(p, deadline_ms, inputs, n_in, outputs, n_out);
}

void PD_TensorsDestroy(PD_Tensor* ts, int n) {
  if (ts == nullptr) return;
  for (int i = 0; i < n; ++i) std::free(ts[i].data);
  std::free(ts);
}

}  // extern "C"
