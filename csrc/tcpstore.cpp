// TCPStore — rendezvous key-value store for multi-host bring-up.
//
// Reference parity: paddle/fluid/distributed/store/tcp_store.{h,cc} +
// tcp_utils.cc (master socket accepting SET/GET/ADD/WAIT ops used to
// exchange bootstrap ids). The TPU build uses it to exchange the
// jax.distributed coordinator address and for barrier() across hosts when
// no cluster scheduler provides a store.
//
// Protocol (little-endian):
//   u8 op {0=SET,1=GET,2=ADD,3=WAIT,4=PING}
//   u32 key_len, key bytes
//   SET: u32 val_len, val bytes            -> reply u8 1
//   GET: -> reply u32 val_len (0xFFFFFFFF if missing), val bytes
//   ADD: i64 delta                         -> reply i64 new_value
//   WAIT:                                  -> reply u8 1 once key exists
//   PING:                                  -> reply u8 1
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::vector<uint8_t>> data;
  std::map<std::string, int64_t> counters;
};

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class Server {
 public:
  Server() : stop_(false), listen_fd_(-1), port_(0) {}

  int Start(int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
      return -1;
    if (::listen(listen_fd_, 128) < 0) return -1;
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return port_;
  }

  void Stop() {
    stop_ = true;
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : workers_)
      if (t.joinable()) t.join();
  }

  ~Server() { Stop(); }

 private:
  void AcceptLoop() {
    while (!stop_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      workers_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    while (!stop_) {
      uint8_t op;
      if (!read_full(fd, &op, 1)) break;
      uint32_t klen;
      if (op != 4 && !read_full(fd, &klen, 4)) break;
      std::string key;
      if (op != 4) {
        key.resize(klen);
        if (!read_full(fd, key.data(), klen)) break;
      }
      if (op == 0) {  // SET
        uint32_t vlen;
        if (!read_full(fd, &vlen, 4)) break;
        std::vector<uint8_t> val(vlen);
        if (!read_full(fd, val.data(), vlen)) break;
        {
          std::lock_guard<std::mutex> g(store_.mu);
          store_.data[key] = std::move(val);
        }
        store_.cv.notify_all();
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1)) break;
      } else if (op == 1) {  // GET
        std::vector<uint8_t> val;
        bool found = false;
        {
          std::lock_guard<std::mutex> g(store_.mu);
          auto it = store_.data.find(key);
          if (it != store_.data.end()) {
            val = it->second;
            found = true;
          }
        }
        uint32_t vlen = found ? static_cast<uint32_t>(val.size()) : 0xFFFFFFFFu;
        if (!write_full(fd, &vlen, 4)) break;
        if (found && !write_full(fd, val.data(), val.size())) break;
      } else if (op == 2) {  // ADD
        int64_t delta;
        if (!read_full(fd, &delta, 8)) break;
        int64_t now;
        {
          std::lock_guard<std::mutex> g(store_.mu);
          now = (store_.counters[key] += delta);
        }
        store_.cv.notify_all();
        if (!write_full(fd, &now, 8)) break;
      } else if (op == 3) {  // WAIT (blocks until key exists as data or counter)
        std::unique_lock<std::mutex> g(store_.mu);
        store_.cv.wait(g, [&] {
          return stop_ || store_.data.count(key) || store_.counters.count(key);
        });
        g.unlock();
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1)) break;
      } else if (op == 4) {  // PING
        uint8_t ok = 1;
        if (!write_full(fd, &ok, 1)) break;
      } else {
        break;
      }
    }
    ::close(fd);
  }

  Store store_;
  std::atomic<bool> stop_;
  int listen_fd_;
  int port_;
  std::thread accept_thread_;
  std::vector<std::thread> workers_;
};

class Client {
 public:
  int Connect(const char* host, int port, int timeout_ms) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) return -1;
    // retry-connect within the timeout (server may come up later)
    int waited = 0;
    while (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      ::close(fd_);
      if (waited >= timeout_ms) return -1;
      ::usleep(100 * 1000);
      waited += 100;
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    }
    int one = 1;
    ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    return 0;
  }

  int Set(const char* key, const uint8_t* val, uint32_t vlen) {
    uint8_t op = 0;
    uint32_t klen = static_cast<uint32_t>(strlen(key));
    if (!write_full(fd_, &op, 1) || !write_full(fd_, &klen, 4) ||
        !write_full(fd_, key, klen) || !write_full(fd_, &vlen, 4) ||
        !write_full(fd_, val, vlen))
      return -1;
    uint8_t ok;
    return read_full(fd_, &ok, 1) ? 0 : -1;
  }

  // returns value length, -1 missing, -2 error; copies into buf (cap bytes)
  int64_t Get(const char* key, uint8_t* buf, uint32_t cap) {
    uint8_t op = 1;
    uint32_t klen = static_cast<uint32_t>(strlen(key));
    if (!write_full(fd_, &op, 1) || !write_full(fd_, &klen, 4) ||
        !write_full(fd_, key, klen))
      return -2;
    uint32_t vlen;
    if (!read_full(fd_, &vlen, 4)) return -2;
    if (vlen == 0xFFFFFFFFu) return -1;
    std::vector<uint8_t> val(vlen);
    if (!read_full(fd_, val.data(), vlen)) return -2;
    if (vlen <= cap) memcpy(buf, val.data(), vlen);
    return static_cast<int64_t>(vlen);
  }

  int64_t Add(const char* key, int64_t delta) {
    uint8_t op = 2;
    uint32_t klen = static_cast<uint32_t>(strlen(key));
    if (!write_full(fd_, &op, 1) || !write_full(fd_, &klen, 4) ||
        !write_full(fd_, key, klen) || !write_full(fd_, &delta, 8))
      return INT64_MIN;
    int64_t now;
    return read_full(fd_, &now, 8) ? now : INT64_MIN;
  }

  int Wait(const char* key) {
    uint8_t op = 3;
    uint32_t klen = static_cast<uint32_t>(strlen(key));
    if (!write_full(fd_, &op, 1) || !write_full(fd_, &klen, 4) ||
        !write_full(fd_, key, klen))
      return -1;
    uint8_t ok;
    return read_full(fd_, &ok, 1) ? 0 : -1;
  }

  ~Client() {
    if (fd_ >= 0) ::close(fd_);
  }

 private:
  int fd_ = -1;
};

}  // namespace

extern "C" {

void* tcpstore_server_start(int port, int* out_port) {
  auto* s = new Server();
  int p = s->Start(port);
  if (p < 0) {
    delete s;
    return nullptr;
  }
  if (out_port) *out_port = p;
  return s;
}

void tcpstore_server_stop(void* server) {
  delete static_cast<Server*>(server);
}

void* tcpstore_client_connect(const char* host, int port, int timeout_ms) {
  auto* c = new Client();
  if (c->Connect(host, port, timeout_ms) != 0) {
    delete c;
    return nullptr;
  }
  return c;
}

void tcpstore_client_free(void* client) { delete static_cast<Client*>(client); }

int tcpstore_set(void* client, const char* key, const uint8_t* val, uint32_t len) {
  return static_cast<Client*>(client)->Set(key, val, len);
}

int64_t tcpstore_get(void* client, const char* key, uint8_t* buf, uint32_t cap) {
  return static_cast<Client*>(client)->Get(key, buf, cap);
}

int64_t tcpstore_add(void* client, const char* key, int64_t delta) {
  return static_cast<Client*>(client)->Add(key, delta);
}

int tcpstore_wait(void* client, const char* key) {
  return static_cast<Client*>(client)->Wait(key);
}

}  // extern "C"
