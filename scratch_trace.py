import sys, time, glob, os
import numpy as np
import jax, jax.numpy as jnp
sys.path.insert(0, ".")
import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.jit.functional import functional_call, split_state

paddle.seed(0)
net = models.resnet50(data_format="NHWC"); net.eval()
trainable, frozen = split_state(net)
pnames, bnames = list(trainable), list(frozen)
dtype = jnp.bfloat16
p = [trainable[n]._value.astype(dtype) if jnp.issubdtype(trainable[n]._value.dtype, jnp.floating) else trainable[n]._value for n in pnames]
b = [frozen[n]._value.astype(dtype) if jnp.issubdtype(frozen[n]._value.dtype, jnp.floating) else frozen[n]._value for n in bnames]

@jax.jit
def f(x):
    out = functional_call(net, pnames, p, bnames, b, paddle.Tensor(x))
    return out._value if hasattr(out, "_value") else out

x = jnp.asarray(np.random.rand(128, 224, 224, 3).astype(np.float32)).astype(dtype)
r = f(x); float(np.asarray(r.reshape(-1)[0]))
os.makedirs("/root/repo/_trace", exist_ok=True)
with jax.profiler.trace("/root/repo/_trace"):
    for _ in range(20):
        r = f(x)
    float(np.asarray(r.reshape(-1)[0]))
print("trace files:", glob.glob("/root/repo/_trace/**/*", recursive=True)[:10])
